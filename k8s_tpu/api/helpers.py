"""API helpers (reference: pkg/apis/tensorflow/helper/helpers.go)."""

from __future__ import annotations

from k8s_tpu.api.meta import OwnerReference
from k8s_tpu.api.v1alpha1 import types as v1


def as_owner(tfjob) -> OwnerReference:
    """Controller OwnerReference for resources owned by a TFJob
    (helpers.go:36-48).  Works for either API version."""
    return OwnerReference(
        api_version=tfjob.api_version,
        kind=tfjob.kind,
        name=tfjob.metadata.name,
        uid=tfjob.metadata.uid,
        controller=True,
        block_owner_deletion=True,
    )


def crd_name() -> str:
    """`tfjobs.kubeflow.org` (helpers.go:114-116)."""
    return f"{v1.CRD_KIND_PLURAL}.{v1.CRD_GROUP}"


def configure_accelerators_for_tfjob_spec(
    spec: v1.TFJobSpec, accelerators: dict[str, v1.AcceleratorConfig]
) -> None:
    """ConfigureAcceleratorsForTFJobSpec (helpers.go:50-104): for each replica's
    `tensorflow` container, if a resource limit/request name matches a
    configured accelerator, inject its host-path volumes + env vars.

    Kept for GPU-manifest compatibility.  TPU slice hosts need no driver
    mounts — their topology config travels via env (launcher contract), so
    `cloud-tpus.google.com/*` limits typically have no AcceleratorConfig
    entry.
    """
    for r in spec.replica_specs:
        if r.template is None:
            raise ValueError(f"Replica is missing Template; {r}")
        pod_spec = r.template.setdefault("spec", {})
        for c in pod_spec.get("containers") or []:
            if c.get("name") != v1.DEFAULT_TF_CONTAINER:
                continue
            resources = c.get("resources") or {}
            matched: dict[str, v1.AcceleratorConfig] = {}
            for res_list in (resources.get("limits"), resources.get("requests")):
                for name in res_list or {}:
                    if name in accelerators:
                        matched[name] = accelerators[name]
            for config in matched.values():
                for vol in config.volumes:
                    pod_spec.setdefault("volumes", []).append(
                        {"name": vol.name, "hostPath": {"path": vol.host_path}}
                    )
                    c.setdefault("volumeMounts", []).append(
                        {"name": vol.name, "mountPath": vol.mount_path}
                    )
                for env_var in config.env_vars:
                    c.setdefault("env", []).append(
                        {"name": env_var.name, "value": env_var.value}
                    )
            break


def tpu_chips_per_host(template: dict) -> int:
    """Total `cloud-tpus.google.com/*` chips requested by the pod template's
    containers — the TPU analogue of reading the nvidia.com/gpu limit."""
    total = 0
    for c in ((template.get("spec") or {}).get("containers")) or []:
        limits = ((c.get("resources") or {}).get("limits")) or {}
        for name, qty in limits.items():
            if name.startswith("cloud-tpus.google.com/"):
                total += int(qty)
    return total

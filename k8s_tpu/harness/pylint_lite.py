"""AST + symtable lint — real defect detection without third-party deps.

The reference ran full pylint with a tuned config over every file
(py/py_checks.py:18, .pylintrc) plus gometalinter's analyzer set
(linter_config.json:4-18).  This image ships neither pylint nor pyflakes,
and round 3's fallback was a bare ``compile()`` — a syntax check in
disguise.  This module implements the high-signal subset with near-zero
false positives:

- **undefined-name** (symtable): a name read in some scope that no scope
  binds, the module never defines, and builtins don't provide — the classic
  typo'd-identifier NameError that ``compile()`` happily accepts.
- **unused-import**: module-level imports never referenced anywhere in the
  file (and not re-exported via ``__all__``).
- **mutable-default-arg**: ``def f(x=[])`` / ``{}`` / ``set()`` — shared
  across calls.
- **bare-except**: ``except:`` swallows KeyboardInterrupt/SystemExit.
- **duplicate-dict-key**: a literal key repeated in a dict display.
- **assert-tuple**: ``assert (cond, "msg")`` is always true.
- **is-literal**: ``x is "s"`` / ``x is 3`` — identity on literals.
- **unused-variable**: a function-local assigned but never read (pyflakes
  F841 scope: tuple unpacking, bare annotations, and ``_``-prefixed names
  are exempt; closure reads count as uses).
- **f-string-no-placeholder**: ``f"text"`` with no ``{}`` interpolation.
- **self-compare**: ``x == x`` / ``x is x`` / ``x < x`` on a bare name
  (the NaN idiom ``x != x`` is allowed).

``# noqa`` on a line suppresses its findings (optionally ``# noqa: CODE``).
"""

from __future__ import annotations

import ast
import builtins
import symtable

from k8s_tpu.analysis import astutil

_BUILTIN_NAMES = set(dir(builtins)) | {
    "__file__", "__name__", "__doc__", "__spec__", "__loader__",
    "__package__", "__builtins__", "__debug__", "__annotations__",
    "__path__", "__dict__", "__class__", "__module__", "__qualname__",
    "WindowsError",
}


class Finding:
    def __init__(self, code: str, lineno: int, message: str):
        self.code = code
        self.lineno = lineno
        self.message = message

    def __str__(self):
        return f"{self.lineno}: {self.code}: {self.message}"


# pyflakes/pycodestyle code aliases so existing ``# noqa: F401`` comments
# keep working against this linter's named codes
_NOQA_ALIASES = {
    "unused-import": {"f401"},
    "undefined-name": {"f821"},
    "bare-except": {"e722"},
    "duplicate-dict-key": {"f601", "f602"},
    "unused-variable": {"f841", "w0612"},
    "f-string-no-placeholder": {"f541", "w1309"},
}


# noqa parsing is shared with the concurrency analyzer's walker utilities
_noqa_lines = astutil.noqa_lines


def _module_bindings(tree: ast.Module, table: symtable.SymbolTable) -> set[str]:
    """Names the module scope binds (assignments, defs, imports) plus names
    any nested scope declares ``global`` and assigns."""
    bound = set()
    for sym in table.get_symbols():
        if sym.is_assigned() or sym.is_imported():
            bound.add(sym.get_name())

    class GlobalCollector(ast.NodeVisitor):
        def visit_Global(self, node):
            bound.update(node.names)

    GlobalCollector().visit(tree)
    return bound


def _walk_scopes(table: symtable.SymbolTable):
    stack = [table]
    while stack:
        t = stack.pop()
        yield t
        stack.extend(t.get_children())


def _check_undefined(source: str, path: str, tree: ast.Module) -> list[Finding]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and any(
                a.name == "*" for a in node.names):
            return []  # star import: name set is unknowable statically
    try:
        table = symtable.symtable(source, path, "exec")
    except (SyntaxError, ValueError):
        return []
    module_bound = _module_bindings(tree, table)

    # map line numbers for Name loads so findings point somewhere useful
    load_lines: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            load_lines.setdefault(node.id, node.lineno)

    findings = []
    reported = set()
    for scope in _walk_scopes(table):
        for sym in scope.get_symbols():
            name = sym.get_name()
            if name in reported or not sym.is_referenced():
                continue
            if sym.is_local() or sym.is_parameter() or sym.is_imported():
                continue
            if sym.is_free():
                continue  # bound in an enclosing function scope
            # remaining: global reads — must resolve at module level or in
            # builtins
            if name in module_bound or name in _BUILTIN_NAMES:
                continue
            reported.add(name)
            findings.append(Finding(
                "undefined-name", load_lines.get(name, 1),
                f"undefined name {name!r}"))
    return findings


def _check_ast(tree: ast.Module, module_used: set[str],
               dunder_all: set[str], is_init: bool) -> list[Finding]:
    findings = []
    spec_ids = {id(n.format_spec) for n in ast.walk(tree)
                if isinstance(n, ast.FormattedValue)
                and n.format_spec is not None}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id in ("list", "dict", "set")
                ):
                    findings.append(Finding(
                        "mutable-default", d.lineno,
                        f"mutable default argument in {node.name}()"))
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(Finding(
                "bare-except", node.lineno,
                "bare 'except:' also catches KeyboardInterrupt/SystemExit"))
        elif isinstance(node, ast.Dict):
            seen: dict = {}
            for k in node.keys:
                if isinstance(k, ast.Constant):
                    try:
                        if k.value in seen:
                            findings.append(Finding(
                                "duplicate-dict-key", k.lineno,
                                f"duplicate dict key {k.value!r}"))
                        seen[k.value] = True
                    except TypeError:
                        pass
        elif isinstance(node, ast.Assert):
            if isinstance(node.test, ast.Tuple) and node.test.elts:
                findings.append(Finding(
                    "assert-tuple", node.lineno,
                    "assert on a non-empty tuple is always true"))
        elif isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                # bools/None are singletons — identity is well-defined there
                if isinstance(op, (ast.Is, ast.IsNot)) and isinstance(
                        comp, ast.Constant) and isinstance(
                        comp.value, (str, int, float, bytes, complex)
                ) and not isinstance(comp.value, bool):
                    findings.append(Finding(
                        "is-literal", node.lineno,
                        "identity comparison with a literal; use ==/!="))
            # x == x / x is x / x < x on a bare name: always-constant
            # result, almost certainly a typo for a second variable
            # (NaN-check idiom is x != x — allowed)
            left = node.left
            for op, comp in zip(node.ops, node.comparators):
                if (isinstance(left, ast.Name) and isinstance(comp, ast.Name)
                        and left.id == comp.id
                        and not isinstance(op, ast.NotEq)):
                    findings.append(Finding(
                        "self-compare", node.lineno,
                        f"'{left.id}' compared with itself"))
                left = comp
        elif isinstance(node, ast.JoinedStr):
            # skip format-spec JoinedStrs: {x:.1f} nests a placeholder-free
            # JoinedStr('.1f') inside the FormattedValue — not an f-string
            if id(node) not in spec_ids and not any(
                    isinstance(v, ast.FormattedValue) for v in node.values):
                findings.append(Finding(
                    "f-string-no-placeholder", node.lineno,
                    "f-string without any placeholders"))
    # unused module-level imports (skipped in __init__.py: re-export files
    # bind names precisely so CALLERS can import them)
    if is_init:
        return findings
    for node in tree.body:
        names: list[tuple[str, int]] = []
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                names.append((bound, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                names.append((a.asname or a.name, node.lineno))
        for bound, lineno in names:
            if bound not in module_used and bound not in dunder_all:
                findings.append(Finding(
                    "unused-import", lineno, f"{bound!r} imported but unused"))
    return findings


def _check_unused_locals(tree: ast.Module) -> list[Finding]:
    """Locals assigned but never read (pylint W0612), pure-AST scoping.

    Conservative by construction: STORES are collected only from a
    function's own immediate body (descent stops at nested
    function/class/lambda scopes), while LOADS are collected from the
    ENTIRE subtree — a name read by a nested closure therefore always
    counts as used.  Underscore-prefixed names, parameters, and
    global/nonlocal declarations are exempt; for-loop and except-as
    bindings are included (the unused-binding idiom is ``_``).
    """
    findings = []
    own_body_nodes = astutil.own_scope_nodes

    # tuple/list unpacking is exempt (pyflakes F841 behavior): the
    # B, L, H, D = x.shape idiom DOCUMENTS the shape; partial use is
    # fine.  Applies wherever unpacking binds: assignments, for targets,
    # comprehension generators, and with-items.  Bare annotations
    # (x: int with no value) are declarations, not assignments — exempt.
    exempt: set[int] = set()
    for n in ast.walk(tree):
        targets = []
        if isinstance(n, ast.Assign):
            targets = n.targets
        elif isinstance(n, ast.For):
            targets = [n.target]
        elif isinstance(n, ast.comprehension):
            targets = [n.target]
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            targets = [n.optional_vars]
        elif isinstance(n, ast.AnnAssign) and n.value is None:
            targets = [n.target]
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)) or (
                    isinstance(n, ast.AnnAssign)):
                exempt.update(id(x) for x in ast.walk(t)
                              if isinstance(x, ast.Name))

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        declared: set[str] = set()
        stores: dict[str, int] = {}
        for n in own_body_nodes(fn):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                declared.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store) \
                    and id(n) not in exempt:
                # min(): own_body_nodes walks a stack (reverse order) and
                # the finding must anchor — and noqa must match — the
                # FIRST assignment line
                stores[n.id] = min(stores.get(n.id, n.lineno), n.lineno)
            elif isinstance(n, ast.ExceptHandler) and n.name:
                stores[n.name] = min(stores.get(n.name, n.lineno), n.lineno)
        loads = {n.id for n in ast.walk(fn)
                 if isinstance(n, ast.Name)
                 and not isinstance(n.ctx, ast.Store)}
        for name, lineno in sorted(stores.items(), key=lambda kv: kv[1]):
            if name.startswith("_") or name in declared or name in loads:
                continue
            findings.append(Finding(
                "unused-variable", lineno,
                f"local variable {name!r} assigned but never used"))
    return findings


def check_source(source: str, path: str = "<string>") -> list[Finding]:
    try:
        tree = ast.parse(source, path)
    except SyntaxError as e:
        return [Finding("syntax-error", e.lineno or 1, str(e))]

    module_used: set[str] = set()
    dunder_all: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and not isinstance(node.ctx, ast.Store):
            module_used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # base resolves through a Name node anyway
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    dunder_all.add(elt.value)

    is_init = path.replace("\\", "/").endswith("__init__.py")
    findings = _check_undefined(source, path, tree)
    findings += _check_ast(tree, module_used, dunder_all, is_init)
    findings += _check_unused_locals(tree)

    noqa = _noqa_lines(source)
    kept = []
    for f in findings:
        if f.lineno in noqa:
            codes = noqa[f.lineno]
            if codes is None or (
                ({f.code.lower()} | _NOQA_ALIASES.get(f.code, set())) & codes
            ):
                continue
        kept.append(f)
    kept.sort(key=lambda f: (f.lineno, f.code))
    return kept


def check_file(path: str) -> list[Finding]:
    with open(path, "rb") as f:
        source = f.read().decode("utf-8", "replace")
    return check_source(source, path)

"""Release builder (reference: py/release.py:123-702).

Builds the deployable artifacts for the operator:

- the operator image build context (Dockerfile + ``k8s_tpu`` sources + e2e
  binary entrypoints), via :mod:`k8s_tpu.harness.build_and_push_image`
  (release.py:123-231 ``build_operator_image``),
- the chart package: ``tf-job-operator-chart-<version>.tgz`` with
  ``values.yaml`` rewritten to the new image ref (release.py:53-77
  ``update_values``/``update_chart``),
- ``build_info.yaml`` describing what was built (release.py:288-307).

GCS/gcloud plumbing is replaced by the artifact-store abstraction
(k8s_tpu/harness/artifacts.py), so the same code paths run against a local
directory store.
"""

from __future__ import annotations

import argparse
import logging
import os
import re
import tarfile
import tempfile
import time

import yaml

from k8s_tpu.harness import build_and_push_image

log = logging.getLogger(__name__)

DEFAULT_BASE_IMAGE = "python:3.11-slim"

# The checked-in build context (reference keeps its Dockerfile at
# build/images/tf_operator/Dockerfile:1; ours is a template because the base
# image is substituted at build time).
DOCKERFILE_TEMPLATE_RELPATH = os.path.join(
    "build", "images", "tf_operator", "Dockerfile.template"
)


def dockerfile_template_path(repo_dir: str) -> str:
    path = os.path.join(repo_dir, DOCKERFILE_TEMPLATE_RELPATH)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"missing checked-in Dockerfile template at {path} "
            "(build/images/tf_operator/ is part of the repo, like the "
            "reference's build/images/tf_operator/Dockerfile)"
        )
    return path


def update_values(values_file: str, image: str) -> None:
    """Rewrite the ``image:`` line preserving comments (release.py:53-66)."""
    with open(values_file) as f:
        lines = f.readlines()
    with open(values_file, "w") as f:
        for line in lines:
            if re.match(r"^image:", line):
                f.write(f"image: {image}\n")
            else:
                f.write(line)


def update_chart(chart_file: str, version: str) -> None:
    """Stamp the chart version (release.py:68-77)."""
    with open(chart_file) as f:
        chart = yaml.safe_load(f)
    chart["version"] = version
    with open(chart_file, "w") as f:
        yaml.safe_dump(chart, f, default_flow_style=False)


def build_operator_image(
    repo_dir: str, registry: str, output_dir: str, base_image: str = DEFAULT_BASE_IMAGE
) -> dict:
    """Prepare the operator image context and build it when docker exists
    (release.py:123-231).  Returns {'image': ref, 'context_dir': ...}."""
    import shutil

    context_dir = os.path.join(output_dir, "image-context")
    os.makedirs(context_dir, exist_ok=True)
    for name in ("k8s_tpu", "examples"):
        src = os.path.join(repo_dir, name)
        dst = os.path.join(context_dir, name)
        if os.path.isdir(src):
            # always copy fresh: a stale context from a prior run must not be
            # baked under a new tag
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(
                src, dst,
                # _build holds host-arch artifacts (runtime .so, stress/TSan
                # binaries) that must never be baked into the image
                ignore=shutil.ignore_patterns(
                    "__pycache__", "*.pyc", "*.so", "_build", "*.tmp"
                ),
            )
    # every COPY source the Dockerfile names must be in the context
    shutil.copy2(
        os.path.join(repo_dir, "ci_config.yaml"),
        os.path.join(context_dir, "ci_config.yaml"),
    )
    ref = build_and_push_image.build_and_push(
        dockerfile_template_path(repo_dir),
        context_dir,
        image=f"{registry}/tf-job-operator",
        repo_dir=repo_dir,
        substitutions={"base_image": base_image},
    )
    return {"image": ref, "context_dir": context_dir}


def build_chart_package(repo_dir: str, image: str, version: str, output_dir: str) -> str:
    """Package examples/tf_job_chart with the release image baked into
    values.yaml (the helm-package step, release.py:249-286)."""
    import shutil

    chart_src = os.path.join(repo_dir, "examples", "tf_job_chart")
    os.makedirs(output_dir, exist_ok=True)
    pkg = os.path.join(output_dir, f"tf-job-operator-chart-{version}.tgz")
    with tempfile.TemporaryDirectory(prefix="chart-") as tmp:
        staging = os.path.join(tmp, "tf-job")
        shutil.copytree(chart_src, staging)
        update_values(os.path.join(staging, "values.yaml"), image)
        update_chart(os.path.join(staging, "Chart.yaml"), version)
        with tarfile.open(pkg, "w:gz") as tar:
            tar.add(staging, arcname="tf-job")
    return pkg


def write_build_info(build_info: dict, path: str) -> None:
    """build_info.yaml (release.py:288-307)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(build_info, f, default_flow_style=False)


def build_and_push_artifacts(
    repo_dir: str, registry: str, output_dir: str, version: str | None = None
) -> dict:
    """The full release pipeline (release.py:249-307): image + chart +
    build_info.  ``version`` defaults to 0.1.0+<image tag>."""
    os.makedirs(output_dir, exist_ok=True)
    image_result = build_operator_image(repo_dir, registry, output_dir)
    tag = image_result["image"].rsplit(":", 1)[1]
    version = version or f"0.1.0-{tag}"
    chart_pkg = build_chart_package(repo_dir, image_result["image"], version, output_dir)
    info = {
        "image": image_result["image"],
        "chart": os.path.basename(chart_pkg),
        "version": version,
        "timestamp": int(time.time()),
    }
    write_build_info(info, os.path.join(output_dir, "build_info.yaml"))
    return info


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    subparsers = parser.add_subparsers(dest="command", required=True)
    local = subparsers.add_parser("local", help="build from this checkout (release.py:385)")
    local.add_argument("--registry", default="k8s-tpu")
    local.add_argument("--output_dir", required=True)
    local.add_argument("--src_dir", default=os.getcwd())
    local.add_argument("--version", default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    info = build_and_push_artifacts(
        args.src_dir, args.registry, args.output_dir, version=args.version
    )
    log.info("built: %s", info)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

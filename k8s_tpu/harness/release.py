"""Release builder (reference: py/release.py:123-702).

Builds the deployable artifacts for the operator:

- the operator image build context (Dockerfile + ``k8s_tpu`` sources + e2e
  binary entrypoints), via :mod:`k8s_tpu.harness.build_and_push_image`
  (release.py:123-231 ``build_operator_image``),
- the chart package: ``tf-job-operator-chart-<version>.tgz`` with
  ``values.yaml`` rewritten to the new image ref (release.py:53-77
  ``update_values``/``update_chart``),
- ``build_info.yaml`` describing what was built (release.py:288-307).

GCS/gcloud plumbing is replaced by the artifact-store abstraction
(k8s_tpu/harness/artifacts.py), so the same code paths run against a local
directory store.
"""

from __future__ import annotations

import argparse
import logging
import os
import re
import tarfile
import tempfile
import time

import yaml

from k8s_tpu.harness import build_and_push_image

log = logging.getLogger(__name__)

DEFAULT_BASE_IMAGE = "python:3.11-slim"

# The checked-in build context (reference keeps its Dockerfile at
# build/images/tf_operator/Dockerfile:1; ours is a template because the base
# image is substituted at build time).
DOCKERFILE_TEMPLATE_RELPATH = os.path.join(
    "build", "images", "tf_operator", "Dockerfile.template"
)


def dockerfile_template_path(repo_dir: str) -> str:
    path = os.path.join(repo_dir, DOCKERFILE_TEMPLATE_RELPATH)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"missing checked-in Dockerfile template at {path} "
            "(build/images/tf_operator/ is part of the repo, like the "
            "reference's build/images/tf_operator/Dockerfile)"
        )
    return path


def update_values(values_file: str, image: str) -> None:
    """Rewrite the ``image:`` line preserving comments (release.py:53-66)."""
    with open(values_file) as f:
        lines = f.readlines()
    with open(values_file, "w") as f:
        for line in lines:
            if re.match(r"^image:", line):
                f.write(f"image: {image}\n")
            else:
                f.write(line)


def update_chart(chart_file: str, version: str) -> None:
    """Stamp the chart version (release.py:68-77)."""
    with open(chart_file) as f:
        chart = yaml.safe_load(f)
    chart["version"] = version
    with open(chart_file, "w") as f:
        yaml.safe_dump(chart, f, default_flow_style=False)


def build_operator_image(
    repo_dir: str, registry: str, output_dir: str, base_image: str = DEFAULT_BASE_IMAGE
) -> dict:
    """Prepare the operator image context and build it when docker exists
    (release.py:123-231).  Returns {'image': ref, 'context_dir': ...}."""
    import shutil

    context_dir = os.path.join(output_dir, "image-context")
    os.makedirs(context_dir, exist_ok=True)
    for name in ("k8s_tpu", "examples"):
        src = os.path.join(repo_dir, name)
        dst = os.path.join(context_dir, name)
        if os.path.isdir(src):
            # always copy fresh: a stale context from a prior run must not be
            # baked under a new tag
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(
                src, dst,
                # _build holds host-arch artifacts (runtime .so, stress/TSan
                # binaries) that must never be baked into the image
                ignore=shutil.ignore_patterns(
                    "__pycache__", "*.pyc", "*.so", "_build", "*.tmp"
                ),
            )
    # every COPY source the Dockerfile names must be in the context
    shutil.copy2(
        os.path.join(repo_dir, "ci_config.yaml"),
        os.path.join(context_dir, "ci_config.yaml"),
    )
    ref = build_and_push_image.build_and_push(
        dockerfile_template_path(repo_dir),
        context_dir,
        image=f"{registry}/tf-job-operator",
        repo_dir=repo_dir,
        substitutions={"base_image": base_image},
    )
    return {"image": ref, "context_dir": context_dir}


def build_chart_package(repo_dir: str, image: str, version: str, output_dir: str) -> str:
    """Package examples/tf_job_chart with the release image baked into
    values.yaml (the helm-package step, release.py:249-286)."""
    import shutil

    chart_src = os.path.join(repo_dir, "examples", "tf_job_chart")
    os.makedirs(output_dir, exist_ok=True)
    pkg = os.path.join(output_dir, f"tf-job-operator-chart-{version}.tgz")
    with tempfile.TemporaryDirectory(prefix="chart-") as tmp:
        staging = os.path.join(tmp, "tf-job")
        shutil.copytree(chart_src, staging)
        update_values(os.path.join(staging, "values.yaml"), image)
        update_chart(os.path.join(staging, "Chart.yaml"), version)
        with tarfile.open(pkg, "w:gz") as tar:
            tar.add(staging, arcname="tf-job")
    return pkg


def write_build_info(build_info: dict, path: str) -> None:
    """build_info.yaml (release.py:288-307)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump(build_info, f, default_flow_style=False)


def build_and_push_artifacts(
    repo_dir: str, registry: str, output_dir: str, version: str | None = None,
    extra_info: dict | None = None,
) -> dict:
    """The full release pipeline (release.py:249-307): image + chart +
    build_info.  ``version`` defaults to 0.1.0+<image tag>; ``extra_info``
    keys (e.g. the source commit) are merged into the one build_info.yaml
    write so the file is never on disk incomplete."""
    os.makedirs(output_dir, exist_ok=True)
    image_result = build_operator_image(repo_dir, registry, output_dir)
    tag = image_result["image"].rsplit(":", 1)[1]
    version = version or f"0.1.0-{tag}"
    chart_pkg = build_chart_package(repo_dir, image_result["image"], version, output_dir)
    info = {
        "image": image_result["image"],
        "chart": os.path.basename(chart_pkg),
        "version": version,
        "timestamp": int(time.time()),
        **(extra_info or {}),
    }
    write_build_info(info, os.path.join(output_dir, "build_info.yaml"))
    return info


# -- source selection: which commit gets built (reference release.py's
# clone subcommands, :404-461, over util.clone_repo) ---------------------


def git_clone(repo_url: str, dest: str, commit: str | None = None,
              branches: list[str] | None = None) -> str:
    """Clone ``repo_url`` into ``dest``, fetch any extra refspecs, check out
    ``commit`` if given; returns the checked-out sha (the util.clone_repo
    contract, py/util.py:90-135)."""
    from k8s_tpu.harness import util as harness_util

    harness_util.run(["git", "clone", repo_url, dest])
    for refspec in branches or []:
        harness_util.run(["git", "fetch", "origin", refspec], cwd=dest)
    if commit:
        harness_util.run(["git", "checkout", commit], cwd=dest)
    return harness_util.run_and_output(
        ["git", "rev-parse", "HEAD"], cwd=dest).strip()


def clone_pr(repo_url: str, dest: str, pr: int,
             commit: str | None = None) -> str:
    """Check out a pull request head (release.py:408-410: fetches
    pull/<pr>/head into a local ``pr`` branch)."""
    return git_clone(repo_url, dest, commit or "pr",
                     branches=[f"pull/{pr}/head:pr"])


def clone_postsubmit(repo_url: str, dest: str,
                     commit: str | None = None) -> str:
    """Check out a postsubmit commit (default branch head when None;
    release.py:413-414)."""
    return git_clone(repo_url, dest, commit)


def latest_green_sha(store, job_name: str) -> str:
    """The sha recorded by prow.create_latest for the last passing
    postsubmit (release.py:455-460 get_latest_green_presubmit)."""
    import json

    from k8s_tpu.harness import prow

    payload = store.download_as_string(
        prow.RESULTS_BUCKET, os.path.join(job_name, "latest_green.json"))
    data = json.loads(payload)
    if data.get("status") != "passing" or not data.get("sha"):
        raise ValueError(f"no passing postsubmit recorded: {data}")
    return data["sha"]


def clone_lastgreen(repo_url: str, dest: str, store, job_name: str) -> str:
    """Check out the last green postsubmit (release.py:455-460)."""
    return git_clone(repo_url, dest, latest_green_sha(store, job_name))


def build_at_ref(repo_url: str, registry: str, output_dir: str,
                 clone_fn, version: str | None = None) -> dict:
    """clone → build pipeline shared by the pr/postsubmit/lastgreen modes
    (release.py:419-452 build_commit).  Reruns with the same output_dir
    wipe the previous clone — a stale checkout must not be built under a
    new tag (same contract as build_operator_image's context refresh)."""
    import shutil

    os.makedirs(output_dir, exist_ok=True)
    src_dir = os.path.join(output_dir, "src")
    if os.path.exists(src_dir):
        shutil.rmtree(src_dir)
    sha = clone_fn(repo_url, src_dir)
    return build_and_push_artifacts(src_dir, registry, output_dir,
                                    version=version,
                                    extra_info={"commit": sha})


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    subparsers = parser.add_subparsers(dest="command", required=True)

    local = subparsers.add_parser(
        "local", help="build from this checkout (release.py:385)")
    local.add_argument("--src_dir", default=os.getcwd())

    pr = subparsers.add_parser(
        "pr", help="clone a PR head and build it (release.py:449-452)")
    pr.add_argument("--pr", type=int, required=True)
    pr.add_argument("--commit", default=None)

    post = subparsers.add_parser(
        "postsubmit",
        help="clone a postsubmit commit and build it (release.py:442-444)")
    post.add_argument("--commit", default=None)

    green = subparsers.add_parser(
        "lastgreen",
        help="build the last passing postsubmit (release.py:455-460)")
    green.add_argument("--job_name", required=True)
    green.add_argument(
        "--artifacts_root",
        default=os.getenv("ARTIFACTS_ROOT", "/tmp/k8s_tpu_artifacts"))

    for p in (local, pr, post, green):
        p.add_argument("--registry", default="k8s-tpu")
        p.add_argument("--output_dir", required=True)
        p.add_argument("--version", default=None)
    for p in (pr, post, green):
        p.add_argument("--repo_url", required=True,
                       help="git URL (or local path) to clone")

    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    if args.command == "local":
        info = build_and_push_artifacts(
            args.src_dir, args.registry, args.output_dir, version=args.version)
    elif args.command == "pr":
        info = build_at_ref(
            args.repo_url, args.registry, args.output_dir,
            lambda url, dest: clone_pr(url, dest, args.pr, args.commit),
            version=args.version)
    elif args.command == "postsubmit":
        info = build_at_ref(
            args.repo_url, args.registry, args.output_dir,
            lambda url, dest: clone_postsubmit(url, dest, args.commit),
            version=args.version)
    else:
        from k8s_tpu.harness.artifacts import LocalArtifactStore

        store = LocalArtifactStore(args.artifacts_root)
        info = build_at_ref(
            args.repo_url, args.registry, args.output_dir,
            lambda url, dest: clone_lastgreen(url, dest, store, args.job_name),
            version=args.version)
    log.info("built: %s", info)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Operator benchmark: TFJob time-to-ready and reconcile throughput.

Measures the two operator-attributable numbers BASELINE.md defines:

- **time-to-ready**: submit (tfjobs.create) → every replica pod Running /
  the job's Running condition set (StartTime logic,
  pkg/controller.v2/controller_status.go:45-50 in the reference);
- **reconcile throughput**: jobs/second the controller drives to ready at a
  given concurrency (the reference's design target is O(100) concurrent
  TFJobs per cluster, tf_job_design_doc.md "Requirements and Scale").

Runs against the in-process local cluster (fake apiserver + kubelet
simulator), so the numbers isolate operator overhead from cluster noise.

CLI:  python -m k8s_tpu.harness.bench_operator [--jobs N] [--replicas R]
Prints one JSON line per metric.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def _tpu_job(name: str, namespace: str, replicas: int) -> dict:
    from k8s_tpu.cmd.genjob import tfjob_template

    return tfjob_template(name, namespace, tpu=True, tpu_replicas=replicas)


def _all_replicas_running(job: dict) -> bool:
    """The metric's definition is ALL replica pods Running; the controller's
    startTime is set exactly when running == replicas
    (controller_v2/status.py:110-111, mirroring controller_status.go:45-50).
    The Running *condition* fires at the first running pod — too early."""
    return bool((job.get("status") or {}).get("startTime"))


def bench_time_to_ready(jobs: int = 20, replicas: int = 4,
                        timeout_s: float = 60.0,
                        threadiness: int = 1,
                        resync_period_s: float = 5.0,
                        backend_mode: str = "fake") -> dict:
    """Submit ``jobs`` gang jobs back to back; measure each
    submit→all-replicas-Running latency and the aggregate throughput."""
    if jobs < 1:
        raise ValueError("jobs must be >= 1")
    from k8s_tpu.e2e.local import LocalCluster

    ns = "bench"
    latencies = []
    # runtime long enough that jobs stay Running while we poll
    # resync default: 5 s. The e2e default (0.1 s) re-enqueues EVERY job
    # 10x/s — at 200+ concurrent jobs the resync storm, not event handling,
    # dominated; the reference runs 30 s (server.go:86), so a bench-scale
    # 5 s keeps the periodic-reconcile backstop without measuring it.
    # backend_mode="rest" runs the whole bench over the wire protocol
    # (HTTP apiserver fixture): the deployed-operator data path, including
    # serialization and watch streaming costs the fake backend skips.
    with LocalCluster(version="v1alpha2", namespace=ns,
                      enable_gang_scheduling=True,
                      kubelet_kwargs={"default_runtime_s": timeout_s},
                      threadiness=threadiness,
                      resync_period_s=resync_period_s,
                      backend_mode=backend_mode) as lc:
        # Watch-based readiness tracking: the poller's list() deep-copied
        # every job per 10 ms tick, which at 300+ concurrent jobs consumed
        # the core being measured.  A watch costs one event per status
        # transition — the bench now observes the operator instead of
        # competing with it.
        from k8s_tpu.client.gvr import TFJOBS_V1ALPHA2

        # NOTE (--backend rest): _RestWatch.next() blocks on the stream
        # rather than honoring the poll timeout, so on a stalled run the
        # deadline check can overshoot --timeout by up to the server
        # watch timeout.
        w = lc.backend.watch(TFJOBS_V1ALPHA2, ns)
        try:
            t_all0 = time.perf_counter()
            pending = {}
            for i in range(jobs):
                name = f"bench-{i}"
                lc.clientset.tfjobs_unstructured(ns).create(
                    _tpu_job(name, ns, replicas))
                pending[name] = time.perf_counter()

            deadline = time.perf_counter() + timeout_s
            while pending and time.perf_counter() < deadline:
                item = w.next(timeout=0.2)
                if item is None:
                    continue
                _etype, job = item
                name = (job.get("metadata") or {}).get("name")
                if name in pending and _all_replicas_running(job):
                    latencies.append(time.perf_counter() - pending.pop(name))
            elapsed_all = time.perf_counter() - t_all0
        finally:
            w.stop()

    if pending:
        raise RuntimeError(
            f"{len(pending)} of {jobs} jobs never reached Running in "
            f"{timeout_s}s: {sorted(pending)[:5]}")
    return {
        "jobs": jobs,
        "replicas": replicas,
        "time_to_ready_p50_s": round(statistics.median(latencies), 4),
        "time_to_ready_max_s": round(max(latencies), 4),
        "jobs_per_sec": round(jobs / elapsed_all, 2),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=20)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--threadiness", type=int, default=1,
                   help="controller worker threads (operator --threadiness)")
    p.add_argument("--resync", type=float, default=5.0,
                   help="informer resync period seconds (reference: 30)")
    p.add_argument("--backend", choices=["fake", "rest"], default="fake",
                   help="fake = in-process store; rest = full HTTP wire "
                   "protocol through the apiserver fixture")
    args = p.parse_args(argv)

    result = bench_time_to_ready(args.jobs, args.replicas, args.timeout,
                                 threadiness=args.threadiness,
                                 resync_period_s=args.resync,
                                 backend_mode=args.backend)
    print(json.dumps({"metric": "tfjob_time_to_ready_p50",
                      "value": result["time_to_ready_p50_s"],
                      "unit": "s", "backend": args.backend, **result}))

    from k8s_tpu.client import rest

    if rest.WIRE_PROFILE_ENABLED and args.backend == "rest":
        # K8S_TPU_WIRE_PROFILE=1: the per-verb budget behind the
        # rest-vs-fake ratio (BASELINE.md wire-floor arithmetic)
        profile = rest.wire_profile_snapshot()
        total_calls = sum(v["count"] for v in profile.values())
        total_s = sum(v["seconds"] for v in profile.values())
        # counters are process-wide for the cluster's whole lifetime, so
        # the per-job figure AMORTIZES fixed startup traffic (informer
        # bootstrap LISTs etc.) — negligible at hundreds of jobs, dominant
        # at --jobs 1
        print(json.dumps({
            "metric": "wire_profile",
            "requests_total": total_calls,
            "requests_per_job_amortized": round(total_calls / args.jobs, 1),
            "client_seconds_total": round(total_s, 3),
            "mean_us_per_call": round(1e6 * total_s / max(total_calls, 1)),
            "by_verb": profile,
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Operator benchmark: TFJob time-to-ready and reconcile throughput.

Measures the two operator-attributable numbers BASELINE.md defines:

- **time-to-ready**: submit (tfjobs.create) → every replica pod Running /
  the job's Running condition set (StartTime logic,
  pkg/controller.v2/controller_status.go:45-50 in the reference);
- **reconcile throughput**: jobs/second the controller drives to ready at a
  given concurrency (the reference's design target is O(100) concurrent
  TFJobs per cluster, tf_job_design_doc.md "Requirements and Scale").

Runs against the in-process local cluster (fake apiserver + kubelet
simulator), so the numbers isolate operator overhead from cluster noise.

CLI:  python -m k8s_tpu.harness.bench_operator [--jobs N] [--replicas R]
Prints one JSON line per metric.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def _tpu_job(name: str, namespace: str, replicas: int) -> dict:
    return {
        "apiVersion": "kubeflow.org/v1alpha2",
        "kind": "TFJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"tfReplicaSpecs": {"TPU": {
            "replicas": replicas,
            "template": {"spec": {"containers": [{
                "name": "tensorflow",
                "image": "k8s-tpu/bench:latest",
                "ports": [{"name": "tfjob-port", "containerPort": 2222}],
                "resources": {"limits": {"cloud-tpus.google.com/v5e": 4}},
            }]}},
        }}},
    }


def _running_condition_set(job: dict) -> bool:
    for c in ((job.get("status") or {}).get("conditions")) or []:
        if c.get("type") == "Running" and c.get("status") == "True":
            return True
    return False


def bench_time_to_ready(jobs: int = 20, replicas: int = 4,
                        timeout_s: float = 60.0) -> dict:
    """Submit ``jobs`` gang jobs back to back; measure each submit→Running
    latency and the aggregate throughput."""
    from k8s_tpu.e2e.local import LocalCluster

    ns = "bench"
    latencies = []
    # runtime long enough that jobs stay Running while we poll
    with LocalCluster(version="v1alpha2", namespace=ns,
                      enable_gang_scheduling=True,
                      kubelet_kwargs={"default_runtime_s": timeout_s}) as lc:
        t_all0 = time.perf_counter()
        submitted = []
        for i in range(jobs):
            name = f"bench-{i}"
            lc.clientset.tfjobs_unstructured(ns).create(
                _tpu_job(name, ns, replicas))
            submitted.append((name, time.perf_counter()))

        pending = dict(submitted)
        deadline = time.perf_counter() + timeout_s
        while pending and time.perf_counter() < deadline:
            for name in list(pending):
                job = lc.clientset.tfjobs_unstructured(ns).get(name)
                if job is not None and _running_condition_set(job):
                    latencies.append(time.perf_counter() - pending.pop(name))
            time.sleep(0.01)
        elapsed_all = time.perf_counter() - t_all0

    if pending:
        raise RuntimeError(
            f"{len(pending)} of {jobs} jobs never reached Running in "
            f"{timeout_s}s: {sorted(pending)[:5]}")
    return {
        "jobs": jobs,
        "replicas": replicas,
        "time_to_ready_p50_s": round(statistics.median(latencies), 4),
        "time_to_ready_max_s": round(max(latencies), 4),
        "jobs_per_sec": round(jobs / elapsed_all, 2),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=20)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--timeout", type=float, default=60.0)
    args = p.parse_args(argv)

    result = bench_time_to_ready(args.jobs, args.replicas, args.timeout)
    print(json.dumps({"metric": "tfjob_time_to_ready_p50",
                      "value": result["time_to_ready_p50_s"],
                      "unit": "s", **result}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Operator benchmark: TFJob time-to-ready and reconcile throughput.

Measures the two operator-attributable numbers BASELINE.md defines:

- **time-to-ready**: submit (tfjobs.create) → every replica pod Running /
  the job's Running condition set (StartTime logic,
  pkg/controller.v2/controller_status.go:45-50 in the reference);
- **reconcile throughput**: jobs/second the controller drives to ready at a
  given concurrency (the reference's design target is O(100) concurrent
  TFJobs per cluster, tf_job_design_doc.md "Requirements and Scale").

Runs against the in-process local cluster (fake apiserver + kubelet
simulator), so the numbers isolate operator overhead from cluster noise.

CLI:  python -m k8s_tpu.harness.bench_operator [--jobs N] [--replicas R]
Prints one JSON line per metric.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def _tpu_job(name: str, namespace: str, replicas: int) -> dict:
    from k8s_tpu.cmd.genjob import tfjob_template

    return tfjob_template(name, namespace, tpu=True, tpu_replicas=replicas)


def _worker_gang_job(name: str, namespace: str, replicas: int) -> dict:
    """Worker gang of arbitrary size for the slice-scale fan-out scenario:
    a single v5e slice tops out at 64 hosts (genjob.v5e_slice_for_hosts),
    but the creation fan-out under test is type-agnostic — a 256-replica
    Worker gang exercises exactly the same create path a multislice TPU
    deployment would, without faking an impossible topology."""
    return {
        "apiVersion": "kubeflow.org/v1alpha2",
        "kind": "TFJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "tfReplicaSpecs": {
                "Worker": {
                    "replicas": replicas,
                    "template": {
                        "spec": {
                            "containers": [
                                {
                                    "name": "tensorflow",
                                    "image": "k8s-tpu/smoke:latest",
                                    "ports": [{"name": "tfjob-port",
                                               "containerPort": 2222}],
                                }
                            ]
                        }
                    },
                }
            }
        },
    }


def _tpu_gang_job(name: str, namespace: str, replicas: int) -> dict:
    """SPMD TPU gang of arbitrary size for the restart scenario (gang
    restart is type-gated to TPU replicas, so a Worker gang won't exercise
    the teardown wave): a single v5e slice up to 64 hosts, multislice
    (``numSlices``) beyond — 256 replicas is 4 x v5litepod-256, the
    all-or-nothing restart domain the teardown fan-out exists for."""
    from k8s_tpu.cmd.genjob import V5E_MAX_HOSTS, tfjob_template

    # largest power-of-two slice that fits (v5e topology constraint); any
    # remainder is expressed as extra slices — the operator only cares that
    # the replica count matches what the bench asks for
    hosts = 1 << (min(replicas, V5E_MAX_HOSTS).bit_length() - 1)
    job = tfjob_template(name, namespace, tpu=True, tpu_replicas=hosts)
    if replicas != hosts:
        job["spec"]["tpu"]["numSlices"] = -(-replicas // hosts)
        job["spec"]["tfReplicaSpecs"]["TPU"]["replicas"] = replicas
    return job


def _all_replicas_running(job: dict) -> bool:
    """The metric's definition is ALL replica pods Running; the controller's
    startTime is set exactly when running == replicas
    (controller_v2/status.py:110-111, mirroring controller_status.go:45-50).
    The Running *condition* fires at the first running pod — too early."""
    return bool((job.get("status") or {}).get("startTime"))


# nearest-rank quantile over raw samples (no interpolation surprises at
# the tiny sample counts a bench round produces) — the ONE shared
# implementation, also the serve bench's and the request recorder's
from k8s_tpu.util.util import quantile_nearest as _quantile  # noqa: E402


def bench_time_to_ready(jobs: int = 20, replicas: int = 4,
                        timeout_s: float = 60.0,
                        threadiness: int = 1,
                        resync_period_s: float = 5.0,
                        backend_mode: str = "fake",
                        create_delay_s: float = 0.0,
                        create_concurrency: int | None = None,
                        delete_delay_s: float = 0.0,
                        delete_concurrency: int | None = None) -> dict:
    """Submit ``jobs`` gang jobs back to back; measure each
    submit→all-replicas-Running latency and the aggregate throughput.

    ``create_delay_s``/``delete_delay_s`` inject per-create/per-delete RTTs
    into the fake backend (the apiserver-round-trip model the fan-out
    comparisons need); ``create_concurrency``/``delete_concurrency`` pin the
    controller's fan-out widths (1 = the serial baselines, None =
    production defaults)."""
    if jobs < 1:
        raise ValueError("jobs must be >= 1")
    from k8s_tpu.e2e.local import LocalCluster

    ns = "bench"
    latencies = []
    sync_latencies: list[float] = []
    # runtime long enough that jobs stay Running while we poll
    # resync default: 5 s. The e2e default (0.1 s) re-enqueues EVERY job
    # 10x/s — at 200+ concurrent jobs the resync storm, not event handling,
    # dominated; the reference runs 30 s (server.go:86), so a bench-scale
    # 5 s keeps the periodic-reconcile backstop without measuring it.
    # backend_mode="rest" runs the whole bench over the wire protocol
    # (HTTP apiserver fixture): the deployed-operator data path, including
    # serialization and watch streaming costs the fake backend skips.
    lc = LocalCluster(version="v1alpha2", namespace=ns,
                      enable_gang_scheduling=True,
                      kubelet_kwargs={"default_runtime_s": timeout_s},
                      threadiness=threadiness,
                      resync_period_s=resync_period_s,
                      backend_mode=backend_mode,
                      create_concurrency=create_concurrency,
                      create_delay_s=create_delay_s,
                      delete_concurrency=delete_concurrency,
                      delete_delay_s=delete_delay_s)
    # Per-sync latency accounting: wrap the sync seam before workers start
    # so every pass lands one raw sample (histogram buckets can't give
    # exact p99 at bench sample counts).
    _orig_sync = lc.controller.sync_tfjob

    def _timed_sync(key):
        t0 = time.perf_counter()
        try:
            return _orig_sync(key)
        finally:
            sync_latencies.append(time.perf_counter() - t0)

    lc.controller.sync_tfjob = _timed_sync
    with lc:
        # Watch-based readiness tracking: the poller's list() deep-copied
        # every job per 10 ms tick, which at 300+ concurrent jobs consumed
        # the core being measured.  A watch costs one event per status
        # transition — the bench now observes the operator instead of
        # competing with it.
        from k8s_tpu.client.gvr import TFJOBS_V1ALPHA2

        # NOTE (--backend rest): _RestWatch.next() blocks on the stream
        # rather than honoring the poll timeout, so on a stalled run the
        # deadline check can overshoot --timeout by up to the server
        # watch timeout.
        w = lc.backend.watch(TFJOBS_V1ALPHA2, ns)
        try:
            t_all0 = time.perf_counter()
            pending = {}
            for i in range(jobs):
                name = f"bench-{i}"
                lc.clientset.tfjobs_unstructured(ns).create(
                    _tpu_job(name, ns, replicas))
                pending[name] = time.perf_counter()

            deadline = time.perf_counter() + timeout_s
            while pending and time.perf_counter() < deadline:
                item = w.next(timeout=0.2)
                if item is None:
                    continue
                _etype, job = item
                name = (job.get("metadata") or {}).get("name")
                if name in pending and _all_replicas_running(job):
                    latencies.append(time.perf_counter() - pending.pop(name))
            elapsed_all = time.perf_counter() - t_all0
        finally:
            w.stop()

    if pending:
        raise RuntimeError(
            f"{len(pending)} of {jobs} jobs never reached Running in "
            f"{timeout_s}s: {sorted(pending)[:5]}")
    syncs = sorted(sync_latencies)
    return {
        "jobs": jobs,
        "replicas": replicas,
        "time_to_ready_p50_s": round(statistics.median(latencies), 4),
        "time_to_ready_max_s": round(max(latencies), 4),
        "jobs_per_sec": round(jobs / elapsed_all, 2),
        "sync_count": len(syncs),
        "sync_latency_p50_s": round(_quantile(syncs, 0.50), 4),
        "sync_latency_p99_s": round(_quantile(syncs, 0.99), 4),
    }


def _slice_sync_round(replicas: int, create_latency_s: float,
                      concurrency: int | None) -> dict:
    """One cold first-sync of a single <replicas>-worker gang job against a
    fresh fake cluster with an injected per-create RTT: the pure control-
    plane fan-out cost, no kubelet/informer noise.  Returns the round's
    create count and sync wall time."""
    from k8s_tpu.client.clientset import Clientset
    from k8s_tpu.client.fake import FakeCluster
    from k8s_tpu.client.gvr import PODS, SERVICES
    from k8s_tpu.client.informer import SharedInformerFactory
    from k8s_tpu.client.record import FakeRecorder
    from k8s_tpu.controller_v2.controller import TFJobController

    ns = "bench"
    name = "slice-0"
    fc = FakeCluster()
    fc.create_delay_s = create_latency_s
    cs = Clientset(fc)
    factory = SharedInformerFactory(fc, resync_period=0)
    tc = TFJobController(
        cs,
        informer_factory=factory,
        enable_gang_scheduling=False,
        recorder=FakeRecorder(),
        create_concurrency=concurrency,
    )
    tc.update_status_handler = lambda job: None  # no status API writes
    try:
        fc.create_delay_s = 0.0  # the job submit itself isn't measured
        cs.tfjobs_unstructured(ns).create(_worker_gang_job(name, ns, replicas))
        fc.create_delay_s = create_latency_s
        stored = cs.tfjobs_unstructured(ns).get(name)
        # alwaysReady stores: sync directly, no informer threads
        tc.tfjob_informer.store.replace([stored])
        t0 = time.perf_counter()
        ok = tc.sync_tfjob(f"{ns}/{name}")
        elapsed = time.perf_counter() - t0
        if not ok:
            raise RuntimeError("slice-scale sync did not complete")
        pods = fc.list(PODS, ns)
        services = fc.list(SERVICES, ns)
        names = [p["metadata"]["name"] for p in pods]
        if len(set(names)) != replicas or len(services) != replicas:
            raise RuntimeError(
                f"expected {replicas} unique pods + services, got "
                f"{len(set(names))} pods / {len(services)} services")
        return {"creates": len(pods) + len(services), "sync_s": elapsed}
    finally:
        tc.shutdown()


def bench_slice_scale(replicas: int = 256, create_latency_s: float = 0.01,
                      concurrency: int | None = None, rounds: int = 3,
                      serial_rounds: int = 1) -> dict:
    """Slice-scale creation fan-out: 1 job × ``replicas`` workers, fake
    backend with ``create_latency_s`` injected per create.  Runs the
    parallel path ``rounds`` times and the serial baseline
    ``serial_rounds`` times (the serial sync is O(replicas × RTT) — one
    round of it already costs more wall clock than every parallel round
    together), reporting creates/sec for both plus p50/p99 sync latency."""
    from k8s_tpu.controller_v2 import control as control_mod

    if concurrency is None:
        concurrency = control_mod.create_concurrency_from_env()
    par = [_slice_sync_round(replicas, create_latency_s, concurrency)
           for _ in range(max(1, rounds))]
    with untraced():  # keep baseline spans out of the --trace stage table
        ser = [_slice_sync_round(replicas, create_latency_s, 1)
               for _ in range(max(1, serial_rounds))]

    par_syncs = sorted(r["sync_s"] for r in par)
    par_creates = sum(r["creates"] for r in par)
    par_elapsed = sum(r["sync_s"] for r in par)
    ser_creates = sum(r["creates"] for r in ser)
    ser_elapsed = sum(r["sync_s"] for r in ser)
    par_cps = par_creates / par_elapsed if par_elapsed else 0.0
    ser_cps = ser_creates / ser_elapsed if ser_elapsed else 0.0
    return {
        "replicas": replicas,
        "create_latency_ms": round(create_latency_s * 1e3, 3),
        "concurrency": concurrency,
        "rounds": len(par),
        "creates_per_sec": round(par_cps, 1),
        "serial_creates_per_sec": round(ser_cps, 1),
        "creates_speedup": round(par_cps / ser_cps, 2) if ser_cps else 0.0,
        "sync_latency_p50_s": round(_quantile(par_syncs, 0.50), 4),
        "sync_latency_p99_s": round(_quantile(par_syncs, 0.99), 4),
        "serial_sync_latency_p50_s": round(
            _quantile(sorted(r["sync_s"] for r in ser), 0.50), 4),
    }


def run_slice_scale(args) -> dict:
    """The --slice-scale scenario: serial-vs-parallel creation fan-out at
    1×N gang scale PLUS the 20×4 time-to-ready comparison under the same
    injected create RTT.  Returns one JSON-able dict (bench.py contract:
    metric/value/unit headline + supporting keys)."""
    slice_result = bench_slice_scale(
        replicas=args.slice_replicas,
        create_latency_s=args.create_latency,
        rounds=args.slice_rounds,
    )
    ttr = {}
    for mode, conc in (("parallel", None), ("serial", 1)):
        ctx = untraced() if mode == "serial" else _noop_ctx()
        with ctx:
            r = bench_time_to_ready(
                args.jobs, args.replicas, args.timeout,
                threadiness=args.threadiness, resync_period_s=args.resync,
                backend_mode="fake", create_delay_s=args.create_latency,
                create_concurrency=conc)
        ttr[mode] = r
    p50_par = ttr["parallel"]["time_to_ready_p50_s"]
    p50_ser = ttr["serial"]["time_to_ready_p50_s"]
    return {
        "metric": "operator_creates_per_sec",
        "value": slice_result["creates_per_sec"],
        "unit": "creates/sec",
        **slice_result,
        "ttr_jobs": args.jobs,
        "ttr_replicas": args.replicas,
        "ttr_p50_s": p50_par,
        "ttr_serial_p50_s": p50_ser,
        "ttr_speedup": round(p50_ser / p50_par, 2) if p50_par else 0.0,
        "ttr_sync_latency_p50_s": ttr["parallel"]["sync_latency_p50_s"],
        "ttr_sync_latency_p99_s": ttr["parallel"]["sync_latency_p99_s"],
    }


def _restart_rounds(replicas: int, delete_latency_s: float,
                    delete_concurrency: int | None, rounds: int,
                    timeout_s: float) -> list[float]:
    """``rounds`` kill-to-all-Running samples against one local cluster:
    bring up a TPU gang, wait until every replica is Running, then per round
    fail one member retryably (SIGTERM/143, the preemption signature) and
    measure until a full gang of NEW pods is Running again.  The injected
    per-delete RTT (``FakeCluster.delete_delay_s``) makes the teardown wave
    the dominant term, so parallel-vs-serial isolates exactly the
    delete fan-out; creates run at RTT 0 in both modes."""
    from k8s_tpu.client.gvr import PODS
    from k8s_tpu.e2e.local import LocalCluster

    ns = "bench"
    samples: list[float] = []
    lc = LocalCluster(version="v1alpha2", namespace=ns,
                      enable_gang_scheduling=True,
                      # synthetic pods must stay Running for the whole bench:
                      # only the injected failure may take a gang member down
                      kubelet_kwargs={"default_runtime_s": 20 * timeout_s},
                      threadiness=1, resync_period_s=5.0,
                      delete_concurrency=delete_concurrency,
                      delete_delay_s=delete_latency_s)
    with lc:
        # Watch-based phase tracking (same rationale as bench_time_to_ready:
        # observe the operator instead of competing with it): one dict of
        # pod name -> phase, fed by the event stream, deleted pods removed.
        w = lc.backend.watch(PODS, ns)
        try:
            phases: dict[str, str] = {}

            def pump_until(pred, deadline: float, what: str) -> None:
                while True:
                    if pred():
                        return
                    if time.perf_counter() >= deadline:
                        raise RuntimeError(
                            f"restart bench: {what} not reached in "
                            f"{timeout_s}s")
                    item = w.next(timeout=0.2)
                    if item is None:
                        continue
                    etype, pod = item
                    name = (pod.get("metadata") or {}).get("name")
                    if etype == "DELETED":
                        phases.pop(name, None)
                    else:
                        phases[name] = (pod.get("status") or {}).get("phase")

            lc.clientset.tfjobs_unstructured(ns).create(
                _tpu_gang_job("restart-0", ns, replicas))
            pump_until(
                lambda: sum(1 for p in phases.values()
                            if p == "Running") >= replicas,
                time.perf_counter() + timeout_s, "initial gang Running")

            for _ in range(max(1, rounds)):
                gen = set(phases)  # the incumbent gang's pod names
                victim = next(n for n, p in phases.items() if p == "Running")
                lc.backend.set_pod_phase(
                    ns, victim, "Failed",
                    containerStatuses=[{
                        "name": "tensorflow",
                        "state": {"terminated": {"exitCode": 143}},
                    }])
                t0 = time.perf_counter()
                # recovered == a FULL gang of new-generation pods Running
                # (the whole gang restarts together: every incumbent is
                # torn down, so no gen-1 name may satisfy the count)
                pump_until(
                    lambda: sum(
                        1 for n, p in phases.items()
                        if p == "Running" and n not in gen) >= replicas,
                    t0 + timeout_s, "gang re-Running after kill")
                samples.append(time.perf_counter() - t0)
        finally:
            w.stop()
    return samples


def bench_restart(replicas: int = 256, delete_latency_s: float = 0.01,
                  delete_concurrency: int | None = None, rounds: int = 3,
                  serial_rounds: int = 1,
                  timeout_s: float = 60.0) -> dict:
    """Gang-restart teardown fan-out: 1 TPU gang x ``replicas``, fake
    backend with ``delete_latency_s`` injected per delete.  Runs the
    parallel teardown ``rounds`` times and the serial baseline
    ``serial_rounds`` times (a serial teardown is O(replicas x RTT) — one
    round of it dominates the whole parallel series), reporting
    kill-to-all-Running p50 for both."""
    from k8s_tpu.controller_v2 import control as control_mod

    if delete_concurrency is None:
        delete_concurrency = control_mod.delete_concurrency_from_env()
    par = _restart_rounds(replicas, delete_latency_s, delete_concurrency,
                          rounds, timeout_s)
    with untraced():  # baseline spans stay out of the --trace stage table
        ser = _restart_rounds(replicas, delete_latency_s, 1,
                              max(1, serial_rounds), timeout_s)
    par_sorted = sorted(par)
    ser_sorted = sorted(ser)
    p50_par = _quantile(par_sorted, 0.50)
    p50_ser = _quantile(ser_sorted, 0.50)
    return {
        "replicas": replicas,
        "delete_latency_ms": round(delete_latency_s * 1e3, 3),
        "delete_concurrency": delete_concurrency,
        "rounds": len(par),
        "kill_to_running_p50_s": round(p50_par, 4),
        "kill_to_running_max_s": round(max(par), 4),
        "serial_kill_to_running_p50_s": round(p50_ser, 4),
        "restart_speedup": round(p50_ser / p50_par, 2) if p50_par else 0.0,
    }


def run_measure_restart(args) -> dict:
    """The --measure-restart scenario: kill-to-all-Running for a 1 x N TPU
    gang under an injected per-delete RTT, parallel vs serial teardown.
    Returns one JSON-able dict (bench.py contract: metric/value/unit
    headline + supporting keys)."""
    r = bench_restart(
        replicas=args.slice_replicas,
        delete_latency_s=args.delete_latency,
        delete_concurrency=args.delete_concurrency,
        rounds=args.restart_rounds,
        timeout_s=args.timeout,
    )
    return {
        "metric": "gang_kill_to_running_p50",
        "value": r["kill_to_running_p50_s"],
        "unit": "s",
        **r,
    }


def bench_contention(jobs: int = 4, replicas: int = 4, hi_priority: int = 10,
                     runtime_s: float = 0.5, cluster_chips: int | None = None,
                     timeout_s: float = 60.0) -> dict:
    """The --contention scenario (ISSUE 4): N equal low-priority TPU gangs
    race for a cluster that fits ONE gang at a time, then a high-priority
    job arrives mid-backlog.  Measures per-job admission latency (submit ->
    gang Running), chip utilization (reserved chip-seconds over the
    makespan, from the scheduler's event ledger), and preemption turnaround
    (high-priority submit -> Running, which includes evicting the victim).
    The headline assertion: the late high-priority job is admitted AHEAD of
    earlier low-priority arrivals still in the queue."""
    from k8s_tpu.client.gvr import TFJOBS_V1ALPHA2
    from k8s_tpu.cmd.genjob import V5E_CHIPS_PER_HOST
    from k8s_tpu.e2e.local import LocalCluster

    if jobs < 2:
        raise ValueError("contention needs >= 2 low-priority jobs")
    ns = "bench"
    chips_per_job = replicas * V5E_CHIPS_PER_HOST
    if cluster_chips is None:
        cluster_chips = chips_per_job  # exactly one gang fits at a time

    def _job(name: str, priority: int) -> dict:
        j = _tpu_gang_job(name, ns, replicas)
        j["spec"]["priority"] = priority
        j["spec"]["queue"] = "prod" if priority else "batch"
        return j

    submit_ts: dict[str, float] = {}
    running_ts: dict[str, float] = {}
    done_ts: dict[str, float] = {}
    queued_seen: set[str] = set()
    lc = LocalCluster(version="v1alpha2", namespace=ns,
                      enable_gang_scheduling=True,
                      kubelet_kwargs={"default_runtime_s": runtime_s},
                      threadiness=1, resync_period_s=0.5,
                      cluster_chips=cluster_chips)
    with lc:
        w = lc.backend.watch(TFJOBS_V1ALPHA2, ns)
        try:
            deadline = time.perf_counter() + timeout_s

            def pump_until(pred, what: str) -> None:
                while not pred():
                    if time.perf_counter() >= deadline:
                        raise RuntimeError(
                            f"contention bench: {what} not reached in "
                            f"{timeout_s}s (running={sorted(running_ts)}, "
                            f"done={sorted(done_ts)})")
                    item = w.next(timeout=0.2)
                    if item is None:
                        continue
                    _etype, jb = item
                    name = (jb.get("metadata") or {}).get("name")
                    status = jb.get("status") or {}
                    conds = {c.get("type"): c.get("status")
                             for c in status.get("conditions") or []}
                    # startTime is set exactly once, when the FIRST full
                    # gang runs — admission latency's end marker
                    if name not in running_ts and status.get("startTime"):
                        running_ts[name] = time.perf_counter()
                    if conds.get("Queued") == "True":
                        queued_seen.add(name)
                    if name not in done_ts and conds.get("Succeeded") == "True":
                        done_ts[name] = time.perf_counter()

            low = [f"lo-{i}" for i in range(jobs)]
            for name in low:
                lc.clientset.tfjobs_unstructured(ns).create(_job(name, 0))
                submit_ts[name] = time.perf_counter()
            # the slice must actually be HELD before the VIP shows up, so
            # the run always exercises preemption, not a lucky free slot
            pump_until(lambda: any(n in running_ts for n in low),
                       "first low-priority gang Running")
            hi = "hi-0"
            lc.clientset.tfjobs_unstructured(ns).create(_job(hi, hi_priority))
            submit_ts[hi] = time.perf_counter()
            everyone = low + [hi]
            pump_until(lambda: all(n in done_ts for n in everyone),
                       "all jobs Succeeded (incl. requeued victims)")
        finally:
            w.stop()
        sched = lc.controller.scheduler
        events = sched.events()
        preemptions = sched.preemptions_total

    waits = sorted(running_ts[n] - submit_ts[n] for n in running_ts)
    hi_wait = running_ts[hi] - submit_ts[hi]
    # admitted ahead of the backlog: some EARLIER low-priority arrival ran
    # only AFTER the late high-priority job
    hi_jumped = any(
        submit_ts[n] < submit_ts[hi] and running_ts[n] > running_ts[hi]
        for n in low
    )
    admission_order = sorted(running_ts, key=running_ts.get)

    # chip utilization over the contended window, from the scheduler's own
    # admit/preempt/release ledger (reservation chip-seconds / capacity)
    busy = 0.0
    open_grants: dict[str, tuple[float, int]] = {}
    tmin, tmax = None, None
    for evt in sorted(events, key=lambda e: e["ts"]):
        ts, etype, key = evt["ts"], evt["type"], evt["key"]
        if etype in ("admit", "adopt"):
            open_grants[key] = (ts, evt["chips"])
            tmin = ts if tmin is None else min(tmin, ts)
        elif etype in ("preempt", "release") and key in open_grants:
            t_open, chips = open_grants.pop(key)
            busy += chips * (ts - t_open)
            tmax = ts if tmax is None else max(tmax, ts)
    makespan = (tmax - tmin) if (tmin is not None and tmax is not None) else 0.0
    utilization = (busy / (cluster_chips * makespan)) if makespan > 0 else 0.0

    return {
        "jobs": jobs + 1,
        "replicas": replicas,
        "cluster_chips": cluster_chips,
        "chips_per_job": chips_per_job,
        "hi_priority": hi_priority,
        "runtime_s": runtime_s,
        "admission_wait_p50_s": round(_quantile(waits, 0.50), 4),
        "admission_wait_max_s": round(waits[-1], 4) if waits else 0.0,
        "hi_admission_wait_s": round(hi_wait, 4),
        "hi_jumped_backlog": hi_jumped,
        "admission_order": admission_order,
        "queued_jobs_observed": len(queued_seen),
        "preemptions": preemptions,
        "preemption_turnaround_s": round(hi_wait, 4) if preemptions else None,
        "utilization": round(utilization, 3),
    }


def run_contention(args) -> dict:
    """The --contention scenario wrapper (bench.py contract: one JSON-able
    dict with a metric/value/unit headline)."""
    r = bench_contention(
        jobs=args.contention_jobs,
        replicas=args.contention_replicas,
        hi_priority=args.contention_priority,
        runtime_s=args.contention_runtime,
        cluster_chips=args.contention_chips,
        timeout_s=args.timeout,
    )
    return {
        "metric": "contention_hi_admission_wait",
        "value": r["hi_admission_wait_s"],
        "unit": "s",
        **r,
    }


def bench_churn(jobs: int = 2000, replicas: int = 1,
                fail_frac: float = 0.05, steady_s: float = 2.0,
                resync_s: float = 1.0, threadiness: int = 4,
                timeout_s: float = 300.0) -> dict:
    """The --churn scenario (ISSUE 7): drive ``jobs`` concurrent TFJobs
    through a create storm, two steady-state windows, and a fail/restart
    storm against FakeCluster, measuring everything through the flight
    recorder — the same ``apiserver_requests_total`` /
    ``watch_relists_total`` substrate a deployed operator exports.

    Embedded assertions (raise on failure — this bench is the scale PROOF
    of ROADMAP item 1, not advisory trend data):

    - **flatness**: steady-state apiserver calls/sec at N jobs stays flat
      vs N/2 jobs (the informer steady state is store reads + status
      no-ops: syncs scale with job count, apiserver calls do NOT);
    - **zero steady LISTs**: no LIST lands on pods/services/tfjobs/nodes
      during either steady window (informer listers serve every sync);
    - **churn cost scales with churn events**: apiserver calls during the
      restart storm stay under a per-event constant independent of N;
    - **relists stay at the expected count**: exactly one ``initial``
      relist per informer, zero 410/error relists through the whole run;
    - **sync p99 bounded**: steady-state sync latency stays store-bound.

    The returned dict carries the ``{verb,resource}`` call breakdown and
    the timeline depth stats (the JSON artifact contract).
    """
    from k8s_tpu import flight
    from k8s_tpu.client.gvr import PODS, TFJOBS_V1ALPHA2
    from k8s_tpu.e2e.local import LocalCluster

    if jobs < 4:
        raise ValueError("churn needs >= 4 jobs (two ramp phases)")
    # a window shorter than two resync periods can legitimately see zero
    # syncs (a tick straddling the window edge) and flake the non-vacuity
    # guard — the measurement needs at least one full resync cycle inside
    if steady_s < 2.0 * resync_s:
        print(json.dumps({
            "note": "churn steady window raised to 2x the resync period",
            "requested_steady_s": steady_s,
            "effective_steady_s": 2.0 * resync_s,
        }), file=sys.stderr)
        steady_s = 2.0 * resync_s
    ns = "bench"
    flight.reset_all()
    # phase-tagged per-sync latencies: the steady-window p99 is the
    # store-bound claim; storm syncs (create waves) are reported separately
    phase = {"name": "ramp"}
    sync_samples: list[tuple[str, float]] = []

    lc = LocalCluster(version="v1alpha2", namespace=ns,
                      enable_gang_scheduling=True,
                      kubelet_kwargs={"default_runtime_s": 20 * timeout_s},
                      threadiness=threadiness, resync_period_s=resync_s)
    # The kubelet simulator's periodic relist fallback is an observer
    # artifact (a real kubelet is watch-driven; the fallback only covers
    # dropped streams, which this bench never produces) — park it so the
    # zero-LIST steady-state assertion measures the OPERATOR, not the
    # test harness's safety net.
    lc.kubelet.RELIST_FALLBACK_S = 100 * timeout_s
    _orig_sync = lc.controller.sync_tfjob

    def _timed_sync(key):
        t0 = time.perf_counter()
        try:
            return _orig_sync(key)
        finally:
            sync_samples.append((phase["name"],
                                 time.perf_counter() - t0))

    lc.controller.sync_tfjob = _timed_sync

    acct = flight.ACCOUNTING

    def _list_total() -> int:
        return acct.count(verb="LIST")

    def _steady_window(label: str) -> dict:
        """One measurement window: no bench-side API traffic at all —
        only the operator's own steady state lands in the accounting."""
        phase["name"] = label
        c0, l0, s0 = acct.total(), _list_total(), len(sync_samples)
        time.sleep(steady_s)
        calls = acct.total() - c0
        return {
            "calls": calls,
            "calls_per_sec": round(calls / steady_s, 2),
            "lists": _list_total() - l0,
            "syncs": len(sync_samples) - s0,
        }

    with lc:
        jw = lc.backend.watch(TFJOBS_V1ALPHA2, ns)
        pw = lc.backend.watch(PODS, ns)
        try:
            ready: set[str] = set()
            # pod name -> (phase, owning job name): fed by the pod watch so
            # the bench never LISTs during a measurement window
            pod_state: dict[str, tuple[str, str]] = {}

            def _apply_pod(et: str, pod: dict) -> None:
                pname = (pod.get("metadata") or {}).get("name")
                owner = next(
                    (r.get("name") for r in
                     (pod.get("metadata") or {}).get(
                         "ownerReferences") or []), "")
                if et == "DELETED":
                    pod_state.pop(pname, None)
                else:
                    pod_state[pname] = (
                        (pod.get("status") or {}).get("phase", ""), owner)

            def _pump(deadline: float, pred, what: str) -> None:
                while not pred():
                    if time.perf_counter() >= deadline:
                        raise RuntimeError(
                            f"churn bench: {what} not reached in "
                            f"{timeout_s}s ({len(ready)} ready)")
                    progressed = False
                    item = jw.next(timeout=0.05)
                    if item is not None:
                        _et, job = item
                        name = (job.get("metadata") or {}).get("name")
                        if _all_replicas_running(job):
                            ready.add(name)
                        progressed = True
                    # drain the pod queue fully: one-event-per-iteration
                    # behind a 50ms job-watch block would throttle pod
                    # state to ~20 events/s and inflate churn recovery
                    while True:
                        item = pw.next(timeout=0.001)
                        if item is None:
                            break
                        _apply_pod(*item)
                        progressed = True
                    if not progressed:
                        time.sleep(0.005)

            def _create(names: list[str]) -> None:
                for name in names:
                    lc.clientset.tfjobs_unstructured(ns).create(
                        _tpu_job(name, ns, replicas))

            all_names = [f"churn-{i}" for i in range(jobs)]
            half = jobs // 2

            phase["name"] = "ramp_half"
            t_ramp0 = time.perf_counter()
            _create(all_names[:half])
            _pump(time.perf_counter() + timeout_s,
                  lambda: len(ready) >= half, "first ramp Running")
            ramp_half_s = time.perf_counter() - t_ramp0

            steady_half = _steady_window("steady_half")

            phase["name"] = "ramp_full"
            t_ramp1 = time.perf_counter()
            _create(all_names[half:])
            _pump(time.perf_counter() + timeout_s,
                  lambda: len(ready) >= jobs, "full ramp Running")
            ramp_full_s = time.perf_counter() - t_ramp1

            steady_full = _steady_window("steady_full")

            # -- churn storm: fail one pod of each victim job -------------
            # drain the pod watch first: readiness is tracked off the JOB
            # watch, so pod MODIFIED events can still be queued when the
            # ramp predicate flips — victim selection needs them applied
            while True:
                item = pw.next(timeout=0.05)
                if item is None:
                    break
                _apply_pod(*item)
            n_events = max(1, int(jobs * fail_frac))
            victims = all_names[:n_events]
            victim_set = set(victims)
            incumbent = {
                pname for pname, (_ph, owner) in pod_state.items()
                if owner in victim_set
            }
            victim_pod = {}
            for pname, (ph, owner) in pod_state.items():
                if owner in victim_set and ph == "Running":
                    victim_pod.setdefault(owner, pname)
            missing = victim_set - set(victim_pod)
            if missing:
                raise RuntimeError(
                    f"churn bench: no Running pod tracked for "
                    f"{len(missing)} victim job(s): {sorted(missing)[:5]}")
            phase["name"] = "churn"
            c0 = acct.total()
            t_churn0 = time.perf_counter()
            # Fault injection runs UNACCOUNTED: set_pod_phase (a GET + PUT
            # per victim) has no real-world analogue — an actual pod
            # failure costs the apiserver nothing.  Everything else in the
            # churn window stays counted, because it all exists in a real
            # deployment: the operator's deletes/creates/status/events AND
            # the kubelet's Running-status PATCH per recovered pod.
            # Thread-local suppression, NOT the backend-wide flag: the
            # operator's worker threads react to the first victims while
            # later ones are still being injected, and their calls must
            # keep counting.
            with flight.suppress_accounting():
                for owner, pname in victim_pod.items():
                    lc.backend.set_pod_phase(
                        ns, pname, "Failed",
                        containerStatuses=[{
                            "name": "tensorflow",
                            "state": {"terminated": {"exitCode": 143}},
                        }])

            def _recovered() -> bool:
                per_job: dict[str, int] = {}
                for pname, (ph, owner) in pod_state.items():
                    if (owner in victim_set and ph == "Running"
                            and pname not in incumbent):
                        per_job[owner] = per_job.get(owner, 0) + 1
                return all(per_job.get(v, 0) >= replicas for v in victims)

            _pump(time.perf_counter() + timeout_s, _recovered,
                  "churned gangs re-Running")
            churn_s = time.perf_counter() - t_churn0
            churn_calls = acct.total() - c0

            steady_post = _steady_window("steady_post")
            # syncs completing during cluster teardown must not be tagged
            # into the last steady window's p99 (a teardown-slowed sync
            # would spuriously fail the store-bound assertion)
            phase["name"] = "teardown"
        finally:
            jw.stop()
            pw.stop()

    # -- assemble + assert ---------------------------------------------------
    steady_syncs = sorted(
        dt for ph, dt in sync_samples
        if ph in ("steady_half", "steady_full", "steady_post"))
    all_syncs = sorted(dt for _ph, dt in sync_samples)
    relists = flight.WATCH.snapshot()["relists"]
    relists_initial = flight.WATCH.relists(reason=flight.RELIST_INITIAL)
    relists_bad = (flight.WATCH.relists(reason=flight.RELIST_EXPIRED)
                   + flight.WATCH.relists(reason=flight.RELIST_ERROR))
    # 4 informers (tfjobs, pods, services, nodes) list exactly once each
    expected_initial = 4
    per_event_calls = churn_calls / n_events
    steady_sync_p99 = _quantile(steady_syncs, 0.99)
    rate_half = steady_half["calls_per_sec"]
    rate_full = steady_full["calls_per_sec"]
    # flatness: going from N/2 to N jobs must not scale the steady-state
    # call rate.  An O(N) regression would DOUBLE the rate, so the
    # tolerance must sit well under 2x (1.25x; the floor of 5 calls/s
    # absorbs timing noise around the expected zero).
    flat_ok = rate_full <= max(1.25 * rate_half, 5.0)

    failures = []
    if not flat_ok:
        failures.append(
            f"steady calls/sec not flat: {rate_half} at {half} jobs -> "
            f"{rate_full} at {jobs} jobs")
    if steady_half["lists"] or steady_full["lists"] or steady_post["lists"]:
        failures.append(
            f"steady-state LISTs detected (informer bypass): "
            f"{steady_half['lists']}/{steady_full['lists']}"
            f"/{steady_post['lists']}")
    if (steady_half["syncs"] + steady_full["syncs"]
            + steady_post["syncs"]) <= 0:
        failures.append("no syncs during any steady window (resync dead — "
                        "the zero-LIST result would be vacuous)")
    if relists_initial != expected_initial or relists_bad:
        failures.append(
            f"relists off: {relists} (expected exactly {expected_initial} "
            f"initial, zero 410/error)")
    if per_event_calls > 40 * max(1, replicas):
        failures.append(
            f"churn cost not event-bound: {per_event_calls:.1f} "
            f"calls/event for {n_events} events")
    if steady_sync_p99 > 0.25:
        failures.append(
            f"steady sync p99 {steady_sync_p99:.3f}s not store-bound")

    # the acceptance artifact: one victim's ordered lifecycle exists
    sample_job = f"{ns}/{victims[0]}"
    sample_timeline = flight.TIMELINE.snapshot(sample_job)
    result = {
        "jobs": jobs,
        "replicas": replicas,
        "churn_events": n_events,
        "ramp_half_s": round(ramp_half_s, 2),
        "ramp_full_s": round(ramp_full_s, 2),
        "jobs_per_sec": round(jobs / (ramp_half_s + ramp_full_s), 1),
        "steady_half": steady_half,
        "steady_full": steady_full,
        "steady_post": steady_post,
        "steady_calls_per_sec_flat": flat_ok,
        "churn_s": round(churn_s, 2),
        "churn_calls": churn_calls,
        "churn_calls_per_event": round(per_event_calls, 1),
        "sync_count": len(all_syncs),
        "sync_latency_p50_s": round(_quantile(all_syncs, 0.50), 4),
        "sync_latency_p99_s": round(_quantile(all_syncs, 0.99), 4),
        "steady_sync_p99_s": round(steady_sync_p99, 4),
        "relists": relists,
        "watch": flight.WATCH.snapshot(),
        "apiserver_calls_total": acct.total(),
        "apiserver_calls_by_verb_resource": acct.by_verb_resource(),
        "timeline_stats": flight.TIMELINE.stats(),
        "sample_job": sample_job,
        "sample_timeline_kinds": [e["kind"] for e in sample_timeline],
    }
    if failures:
        # the measurements are attached to the error so the caller can
        # still write the artifact — a churn regression with no artifact
        # to debug from would defeat the point of the recorder
        result["failures"] = failures
        err = RuntimeError("churn bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


class _FleetPodStubs:
    """N fake serving pods behind ONE loopback HTTP server: each path
    ``/pod/<i>/metrics`` serves a deterministic ``serve_*`` exposition —
    a token counter advancing at a known per-pod rate, a small queue-
    depth gauge, and a latency histogram whose distribution is 98% under
    0.1s / 2% in (0.25, 0.5] (true fleet p99 = 0.375s by interpolation).
    Flipping a pod set to *slow* mode freezes the good counters and
    routes ALL new observations into (1.0, 2.5] — cumulative counters
    never rewrite history, exactly like a real exporter under a latency
    regression.  Float counts by design: the distribution fractions stay
    exact at any elapsed time, so the bench's reference quantile is
    closed-form."""

    OBS_RATE = 200.0  # latency observations per second per pod
    FAST_FRAC = 0.98  # <= 0.1s
    MID_FRAC = 0.02   # (0.25, 0.5]
    TRUE_P99 = 0.375  # 0.25 + 0.25 * (0.99 - 0.98) / 0.02

    def __init__(self, n: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.n = n
        self.t0 = time.monotonic()
        self.rates = [40.0 + 10.0 * (i % 8) for i in range(n)]
        self.depths = [float(i % 5) for i in range(n)]
        # pod index -> monotonic flip time (None = healthy)
        self.slow_since: dict[int, float] = {}
        stubs = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                try:
                    i = int(self.path.split("/")[2])
                    body = stubs.render(i).encode()
                except Exception:  # noqa: BLE001
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Server(ThreadingHTTPServer):
            # the scrape fan-out opens up to K8S_TPU_FLEET_CONCURRENCY
            # connections at once; the default listen backlog of 5 drops
            # SYNs and the kernel's 1s retransmit would dominate the
            # measured cycle cost
            request_queue_size = 128
            daemon_threads = True

        self.httpd = Server(("127.0.0.1", 0), Handler)
        import threading

        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="fleet-stubs")
        self._thread.start()
        self.port = self.httpd.server_address[1]

    def url(self, i: int) -> str:
        return f"http://127.0.0.1:{self.port}/pod/{i}/metrics"

    def flip_slow(self, indices) -> float:
        t = time.monotonic()
        for i in indices:
            self.slow_since.setdefault(i, t)
        return t

    def render(self, i: int) -> str:
        now = time.monotonic()
        el = now - self.t0
        flip = self.slow_since.get(i)
        good_el = el if flip is None else (flip - self.t0)
        slow_el = 0.0 if flip is None else (now - flip)
        fast = self.FAST_FRAC * self.OBS_RATE * good_el
        mid = self.MID_FRAC * self.OBS_RATE * good_el
        slow = self.OBS_RATE * slow_el
        total = fast + mid + slow
        tokens = self.rates[i] * el
        return (
            "# HELP serve_tokens_total Tokens emitted.\n"
            "# TYPE serve_tokens_total counter\n"
            f"serve_tokens_total {tokens}\n"
            "# HELP serve_queue_depth Admission queue depth.\n"
            "# TYPE serve_queue_depth gauge\n"
            f"serve_queue_depth {self.depths[i]}\n"
            "# HELP serve_request_duration_seconds Request latency.\n"
            "# TYPE serve_request_duration_seconds histogram\n"
            f'serve_request_duration_seconds_bucket{{le="0.1"}} {fast}\n'
            f'serve_request_duration_seconds_bucket{{le="0.25"}} {fast}\n'
            f'serve_request_duration_seconds_bucket{{le="0.5"}} '
            f"{fast + mid}\n"
            f'serve_request_duration_seconds_bucket{{le="1.0"}} '
            f"{fast + mid}\n"
            f'serve_request_duration_seconds_bucket{{le="2.5"}} '
            f"{fast + mid + slow}\n"
            f'serve_request_duration_seconds_bucket{{le="+Inf"}} {total}\n'
            f"serve_request_duration_seconds_sum "
            f"{0.05 * fast + 0.375 * mid + 1.75 * slow}\n"
            f"serve_request_duration_seconds_count {total}\n"
        )

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)


def _fleet_gang_job(name: str, namespace: str, replicas: int,
                    scrape_port: int) -> dict:
    """A serving-shaped Worker gang whose pod template carries the fleet
    scrape annotation (what ``genjob --serve`` stamps) — every pod the
    controller creates from it is fleet-discoverable from the informer
    cache alone."""
    job = _worker_gang_job(name, namespace, replicas)
    template = job["spec"]["tfReplicaSpecs"]["Worker"]["template"]
    template.setdefault("metadata", {}).setdefault("annotations", {})[
        "kubeflow.org/fleet-scrape-port"] = str(scrape_port)
    return job


def bench_fleet(pods: int = 32, jobs: int = 4, interval_s: float = 0.25,
                steady_cycles: int = 8, timeout_s: float = 60.0) -> dict:
    """The --fleet scenario (ISSUE 8): ``jobs`` serving TFJobs totalling
    ``pods`` fake serving pods, scraped by the controller's fleet plane,
    with EMBEDDED assertions (raise on failure — this bench is the
    acceptance proof of the telemetry plane, not advisory trend data):

    - **aggregation truth**: each job's fleet ``serve_tokens_total`` rate
      matches the sum of its pods' known per-pod rates within 10%;
    - **quantile truth**: fleet p99 from the merged per-pod histograms
      matches the closed-form reference (0.375s) within 0.02s;
    - **zero apiserver cost**: a steady scraping window adds ZERO
      apiserver calls (flight-recorder-verified — discovery reads the
      informer cache, PR 7's property);
    - **breach latency**: flipping one job's pods to slow latency trips
      the p99 burn-rate rule within two scrape intervals and lands a
      flight-timeline event plus a K8s Event through the aggregating
      recorder;
    - **scrape health**: every target scraped with zero failures and
      cycle cost bounded under the interval.
    """
    import os

    from k8s_tpu import flight
    from k8s_tpu.client.gvr import EVENTS, TFJOBS_V1ALPHA2
    from k8s_tpu.e2e.local import LocalCluster

    if pods < jobs or jobs < 2:
        raise ValueError("--fleet needs >= 2 jobs and >= 1 pod per job")
    replicas = pods // jobs
    pods = replicas * jobs  # keep gangs uniform
    ns = "bench"
    short_w = max(4 * interval_s, 1.0)
    long_w = 4 * short_w
    flight.reset_all()
    stubs = _FleetPodStubs(pods)
    env_overrides = {"K8S_TPU_FLEET_WINDOWS": f"{short_w},{long_w}"}
    saved_env = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)
    try:
        lc = LocalCluster(version="v1alpha2", namespace=ns,
                          enable_gang_scheduling=False,
                          kubelet_kwargs={
                              "default_runtime_s": 20 * timeout_s},
                          threadiness=2, resync_period_s=1.0,
                          fleet_scrape=True, fleet_interval_s=interval_s)
    finally:
        # restored even when construction raises: a leaked 1s/4s window
        # override would quietly reshape later scenarios' SLO math
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    # same rationale as --churn: the kubelet's relist fallback is a
    # harness artifact; park it so the zero-call window measures the
    # operator + fleet plane only
    lc.kubelet.RELIST_FALLBACK_S = 100 * timeout_s
    plane = lc.controller.fleet_plane
    # fake pods have no pod network: rewrite each target's URL onto its
    # loopback stub by (job, replica index) — discovery itself still
    # resolves from the informer cache, which is what's under test
    job_names = [f"fleet-{j}" for j in range(jobs)]
    stub_index = {(f"{ns}/{job_names[j]}", str(r)): j * replicas + r
                  for j in range(jobs) for r in range(replicas)}
    plane.url_override = lambda t: (
        stubs.url(stub_index[(t.job, t.index)])
        if (t.job, t.index) in stub_index else None)

    failures: list[str] = []
    acct = flight.ACCOUNTING
    try:
        with lc:
            jw = lc.backend.watch(TFJOBS_V1ALPHA2, ns)
            try:
                ready: set[str] = set()
                for name in job_names:
                    lc.clientset.tfjobs_unstructured(ns).create(
                        _fleet_gang_job(name, ns, replicas, 9100))
                deadline = time.perf_counter() + timeout_s
                while len(ready) < jobs:
                    if time.perf_counter() >= deadline:
                        raise RuntimeError(
                            f"fleet bench: only {len(ready)}/{jobs} jobs "
                            f"Running in {timeout_s}s")
                    item = jw.next(timeout=0.2)
                    if item is None:
                        continue
                    _et, job = item
                    if _all_replicas_running(job):
                        ready.add((job.get("metadata") or {}).get("name"))
            finally:
                jw.stop()

            # wait for full discovery + first scrapes of every target
            deadline = time.perf_counter() + timeout_s
            while sum(plane.stats.target_count().values()) < pods:
                if time.perf_counter() >= deadline:
                    raise RuntimeError(
                        f"fleet bench: only "
                        f"{sum(plane.stats.target_count().values())}/{pods} "
                        f"targets discovered in {timeout_s}s")
                time.sleep(interval_s / 4)
            # let the rings grow past the short window before measuring
            time.sleep(short_w + 2 * interval_s)

            # -- steady window: zero apiserver calls ----------------------
            c0, l0 = acct.total(), acct.count(verb="LIST")
            cycles0 = plane.stats.cycles
            time.sleep(steady_cycles * interval_s)
            steady_calls = acct.total() - c0
            steady_lists = acct.count(verb="LIST") - l0
            steady_scrape_cycles = plane.stats.cycles - cycles0
            if steady_calls:
                failures.append(
                    f"steady scraping cost {steady_calls} apiserver "
                    f"call(s) ({steady_lists} LISTs) over "
                    f"{steady_scrape_cycles} cycles — discovery must be "
                    "store-only")
            if steady_scrape_cycles < max(1, steady_cycles // 2):
                failures.append(
                    f"scrape loop stalled: {steady_scrape_cycles} cycles "
                    f"in a {steady_cycles}-cycle window")

            # -- aggregation truth ----------------------------------------
            now = time.time()
            rate_checks = {}
            for j, name in enumerate(job_names):
                key = f"{ns}/{name}"
                truth = sum(stubs.rates[j * replicas + r]
                            for r in range(replicas))
                measured = plane.aggregator.counter_rate(
                    key, "serve_tokens_total", short_w, now)
                rate_checks[key] = {
                    "truth": round(truth, 1),
                    "measured": round(measured, 1)
                    if measured is not None else None,
                }
                if measured is None or abs(measured - truth) > 0.10 * truth:
                    failures.append(
                        f"{key}: aggregated tokens/s {measured} vs known "
                        f"per-pod truth {truth} (>10% off)")
            p99_checks = {}
            for name in job_names:
                key = f"{ns}/{name}"
                p99 = plane.aggregator.quantile(
                    key, "serve_request_duration_seconds", 0.99, short_w,
                    now)
                p99_checks[key] = round(p99, 4) if p99 is not None else None
                if p99 is None or abs(p99 - stubs.TRUE_P99) > 0.02:
                    failures.append(
                        f"{key}: fleet p99 {p99} vs reference "
                        f"{stubs.TRUE_P99} (merged-histogram quantile off)")

            # -- breach detection latency ---------------------------------
            victim = f"{ns}/{job_names[0]}"
            t_flip = stubs.flip_slow(range(replicas))
            detect_deadline = time.monotonic() + max(10 * interval_s, 10.0)
            detect_latency = None
            while time.monotonic() < detect_deadline:
                if plane.slo.breached(victim):
                    detect_latency = time.monotonic() - t_flip
                    break
                time.sleep(interval_s / 10)
            breach_budget = 2 * interval_s + max(0.5 * interval_s, 0.3)
            if detect_latency is None:
                failures.append(
                    f"latency breach never tripped the burn-rate rule for "
                    f"{victim}")
            elif detect_latency > breach_budget:
                failures.append(
                    f"breach detected after {detect_latency:.2f}s "
                    f"(> two scrape intervals + slack = "
                    f"{breach_budget:.2f}s)")
            # breached() flips before the evaluator's sinks run (state
            # commits under the lock, sinks fire after the pass), so the
            # timeline entry gets the same grace the Event check below has
            timeline_kinds: list = []
            tl_deadline = time.monotonic() + 5.0
            while time.monotonic() < tl_deadline:
                timeline_kinds = [e["kind"]
                                  for e in flight.TIMELINE.snapshot(victim)]
                if "slo_breach" in timeline_kinds:
                    break
                time.sleep(0.05)
            if "slo_breach" not in timeline_kinds:
                failures.append(
                    f"no slo_breach timeline event for {victim} "
                    f"(kinds: {timeline_kinds})")
            event_seen = False
            event_deadline = time.monotonic() + 5.0
            with flight.suppress_accounting():
                while time.monotonic() < event_deadline and not event_seen:
                    event_seen = any(
                        e.get("reason") == "SloBreach"
                        and (e.get("involvedObject") or {}).get("name")
                        == job_names[0]
                        for e in lc.backend.list(EVENTS, ns))
                    if not event_seen:
                        time.sleep(0.05)
            if not event_seen:
                failures.append(
                    "no SloBreach K8s Event recorded for the victim job")
            healthy_breached = [
                f"{ns}/{n}" for n in job_names[1:]
                if plane.slo.breached(f"{ns}/{n}")]
            if healthy_breached:
                failures.append(
                    f"healthy jobs report SLO breach: {healthy_breached}")

            # -- scrape health / cost bounds ------------------------------
            counts = plane.stats.counts()
            bad = {k: v for k, v in counts.items() if k[1] != "ok"}
            ok_total = sum(v for k, v in counts.items() if k[1] == "ok")
            if bad:
                failures.append(f"non-ok scrape outcomes: {bad}")
            if ok_total < pods * 3:
                failures.append(
                    f"too few successful scrapes: {ok_total} for {pods} "
                    "targets")
            if plane.stats.last_cycle_s > interval_s:
                failures.append(
                    f"scrape cycle cost {plane.stats.last_cycle_s:.3f}s "
                    f"exceeds the {interval_s}s interval at {pods} targets")
            staleness = plane.stats.staleness()
            stale = {j: round(s, 2) for j, s in staleness.items()
                     if s > 3 * interval_s}
            if stale:
                failures.append(f"stale jobs after steady scraping: {stale}")
            summary = plane.summary()
    finally:
        stubs.stop()

    result = {
        "pods": pods,
        "jobs": jobs,
        "replicas": replicas,
        "interval_s": interval_s,
        "windows_s": [short_w, long_w],
        "scrape_cycles": summary["cycles"],
        "last_cycle_s": summary["last_cycle_s"],
        "steady_apiserver_calls": steady_calls,
        "steady_apiserver_lists": steady_lists,
        "steady_scrape_cycles": steady_scrape_cycles,
        "rates": rate_checks,
        "fleet_p99": p99_checks,
        "p99_reference": stubs.TRUE_P99,
        "breach_detect_latency_s": (round(detect_latency, 3)
                                    if detect_latency is not None else None),
        "breach_budget_s": round(breach_budget, 3),
        "breach_timeline_ok": "slo_breach" in timeline_kinds,
        "breach_event_ok": event_seen,
        "scrapes_ok_total": ok_total,
        "apiserver_calls_by_verb_resource": acct.by_verb_resource(),
    }
    if failures:
        result["failures"] = failures
        err = RuntimeError("fleet bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def run_fleet(args) -> dict:
    """The --fleet scenario wrapper (bench.py contract: one JSON-able dict
    with a metric/value/unit headline).  The JSON artifact is written on
    failure too — with a ``failures`` field — matching bench_churn.json."""
    try:
        r = bench_fleet(
            pods=args.fleet_pods,
            jobs=args.fleet_jobs,
            interval_s=args.fleet_interval,
            steady_cycles=args.fleet_steady_cycles,
            timeout_s=max(args.timeout, 60.0),
        )
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.fleet_out, {
                "metric": "fleet_breach_detect_latency",
                "value": partial.get("breach_detect_latency_s"),
                "unit": "s",
                **partial,
            })
        raise
    out = {
        "metric": "fleet_breach_detect_latency",
        "value": r["breach_detect_latency_s"],
        "unit": "s",
        **r,
    }
    _write_artifact(args.fleet_out, out)
    return out


def _write_artifact(path: str | None, payload: dict) -> None:
    """One JSON-line bench artifact writer (churn + serve share it)."""
    if not path:
        return
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps(payload) + "\n")


def run_churn(args) -> dict:
    """The --churn scenario wrapper (bench.py contract: one JSON-able dict
    with a metric/value/unit headline).  The JSON artifact is written on
    failure too — with a ``failures`` field — so a churn regression in the
    non-gating CI tier leaves the numbers behind for whoever debugs it."""
    try:
        r = bench_churn(
            jobs=args.churn_jobs,
            replicas=args.churn_replicas,
            fail_frac=args.churn_fail_frac,
            steady_s=args.churn_steady,
            resync_s=args.churn_resync,
            threadiness=args.churn_threadiness,
            timeout_s=max(args.timeout, 120.0),
        )
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.churn_out, {
                "metric": "churn_steady_calls_per_sec",
                "value": partial["steady_full"]["calls_per_sec"],
                "unit": "calls/sec",
                **partial,
            })
        raise
    out = {
        "metric": "churn_steady_calls_per_sec",
        "value": r["steady_full"]["calls_per_sec"],
        "unit": "calls/sec",
        **r,
    }
    _write_artifact(args.churn_out, out)
    return out


def _write_requests_audit(args, result: dict | None) -> None:
    """The requests_audit.json bench_smoke artifact (ISSUE 12): the
    serve phases' per-phase recorder audits, extracted from the serve
    result — written on failed runs too (the caller passes the partial
    result attached to the assertion error)."""
    path = getattr(args, "requests_audit_out", None)
    if not path or result is None:
        return
    audits = result.get("requests_audit") or {}
    total = sum((a.get("stats") or {}).get("finished_total", 0)
                for a in audits.values())
    _write_artifact(path, {
        "metric": "requests_recorded",
        "value": total,
        "unit": "requests",
        "failures": result.get("failures", []),
        "phases": audits,
    })


def run_serve(args) -> dict:
    """The --serve scenario wrapper: the continuous-batching serving
    bench (harness/bench_serve.py — single-flight vs batched tokens/s
    over real HTTP on the tiny CPU model), emitted on the same one-JSON-
    line contract as the operator scenarios.  Imported lazily: this is
    the only scenario that pulls in JAX.  The artifact is written on
    assertion failure too, ``failures`` field included (the
    bench_churn.json contract); --requests-audit-out additionally lands
    the request-recorder audit artifact either way."""
    from k8s_tpu.harness import bench_serve

    try:
        result = bench_serve.run_bench(
            concurrency=args.serve_concurrency, slots=args.serve_slots,
            requests_per_client=args.serve_requests,
            max_new_short=args.serve_max_new_short,
            max_new_long=args.serve_max_new_long,
            sampled=bool(args.serve_sampled),
            shared_frac=args.serve_shared_frac,
            spec=bool(args.serve_spec),
            draft_k=args.serve_draft_k)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.serve_out, partial)
            _write_requests_audit(args, partial)
        raise
    _write_artifact(args.serve_out, result)
    _write_requests_audit(args, result)
    return result


def run_disagg(args) -> dict:
    """The --disagg scenario wrapper (ISSUE 15): disaggregated
    prefill/decode serving (harness/bench_disagg.py — two REAL engines
    per arm behind the real router, KV block chains migrating over real
    sockets; decode-p99-flat vs collapsed-convoy, fixed-seed
    migrated-vs-local identity, and blocks/s + per-token transfer
    overhead EMBEDDED), on the one-JSON-line contract.  The
    bench_disagg.json artifact is written on assertion failure too,
    ``failures`` included."""
    from k8s_tpu.harness import bench_disagg

    try:
        result = bench_disagg.run_bench(
            shorts=args.disagg_shorts,
            longs=args.disagg_longs,
            duration_s=args.disagg_duration,
            long_len=args.disagg_long_len)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.disagg_out, partial)
        raise
    _write_artifact(args.disagg_out, result)
    return result


def run_kvtier(args) -> dict:
    """The --kvtier scenario wrapper (ISSUE 17): the tiered KV memory
    hierarchy bench (harness/bench_kvtier.py — host-RAM spill tier vs
    evict-recompute on a corpus ~10x the pool's prefix headroom,
    fingerprint-dedup migration storm over real sockets, fixed-seed
    identity through demote->promote and deduped migration on every
    lane EMBEDDED), on the one-JSON-line contract.  The
    bench_kvtier.json artifact is written on assertion failure too,
    ``failures`` included."""
    from k8s_tpu.harness import bench_kvtier

    try:
        result = bench_kvtier.run_bench(
            corpus=args.kvtier_corpus,
            rounds=args.kvtier_rounds,
            spill_mb=args.kvtier_spill_mb,
            storm=args.kvtier_storm)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.kvtier_out, partial)
        raise
    _write_artifact(args.kvtier_out, result)
    return result


def run_serve_mp(args) -> dict:
    """The --serve-mp scenario wrapper (ISSUE 14): the multi-host
    tensor-parallel serving bench (harness/bench_serve_mp.py — a REAL
    1-process vs N-process serving gang over jax.distributed + the
    plan bus, token-identity + mesh-overhead + per-process compile
    budget assertions EMBEDDED), on the one-JSON-line contract.  The
    MULTIPROC artifact trajectory's serving rung; bench_serve_mp.json
    is written on assertion failure too, ``failures`` included."""
    from k8s_tpu.harness import bench_serve_mp

    try:
        result = bench_serve_mp.run_bench(
            processes=args.serve_mp_processes,
            requests=args.serve_mp_requests,
            slots=args.serve_mp_slots,
            threads=args.serve_mp_threads,
            timeout=args.timeout * 10 if args.timeout else 420.0)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.serve_mp_out, partial)
        raise
    _write_artifact(args.serve_mp_out, result)
    return result


class _StubServePod:
    """One fake serving pod behind its own loopback listener: a
    deterministic /v1/generate (tokens are a pure function of prompt +
    seed, so fixed-seed identity through the router is checkable), a
    REAL radix :class:`~k8s_tpu.models.kvblocks.PrefixTree` tracking
    shared-prefix hits at the engine's exact block alignment, slot-
    bounded service time (per-token sleeps, so aggregate tokens/s
    scales with pods), 503 shedding past the queue bound, /healthz, and
    a serve_* /metrics exposition.  ``kill()``/``restart()`` drop and
    re-bind the SAME port — the pod-death/rejoin arm of the router
    bench."""

    def __init__(self, name: str, block_size: int = 8, slots: int = 4,
                 queue_limit: int = 64, per_token_s: float = 0.003,
                 per_prefill_token_s: float = 0.0004,
                 max_new_default: int = 24):
        import threading

        from k8s_tpu.models.kvblocks import PrefixTree

        self.name = name
        self.block_size = block_size
        self.slots = slots
        self.queue_limit = queue_limit
        self.per_token_s = per_token_s
        self.per_prefill_token_s = per_prefill_token_s
        self.max_new_default = max_new_default
        self.tree = PrefixTree(block_size)
        self._tree_lock = threading.Lock()
        self._slots_sem = threading.Semaphore(slots)
        self._state_lock = threading.Lock()
        self.inflight = 0
        self.requests = 0
        self.rejected = 0
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.tokens_total = 0
        self.httpd = None
        self._thread = None
        self.port = 0
        self._socks: set = set()
        self._start(port=0)

    @staticmethod
    def generate_tokens(prompt: list, seed: int, max_new: int) -> list:
        """The deterministic 'model': same (prompt, seed, max_new) ->
        same output on EVERY pod, so routing can never change results."""
        acc = (sum(int(t) for t in prompt) * 31 + seed * 17) % 65536
        return [(acc + i * 7 + int(prompt[i % len(prompt)])) % 256
                for i in range(max_new)]

    def _start(self, port: int) -> None:
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        pod = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # one TCP segment per response (the models/server.py
            # rationale): unbuffered writes + Nagle + delayed ACK would
            # add a ~40ms stall per response and swamp the per-token
            # service times this bench measures
            wbufsize = -1
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.partition("?")[0]
                if path == "/healthz":
                    return self._send(200, {"status": "ok",
                                            "pod": pod.name})
                if path == "/metrics":
                    body = pod.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                return self._send(404, {"error": "unknown path"})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                if self.path.partition("?")[0] != "/v1/generate":
                    return self._send(404, {"error": "unknown path"})
                try:
                    req = json.loads(raw or b"{}")
                    toks = [int(t) for t in req["tokens"]]
                except Exception:  # noqa: BLE001 - client error
                    return self._send(400, {"error": "bad request"})
                code, obj, headers = pod.serve_one(req, toks)
                return self._send(code, obj, headers)

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 128

            def get_request(self):
                # track live client sockets so kill() can sever them:
                # a dead pod drops its keep-alive connections, and the
                # router's health eviction is measured on exactly that
                sock, addr = super().get_request()
                with pod._state_lock:
                    pod._socks.add(sock)
                return sock, addr

            def handle_error(self, request, client_address):
                pass  # killed-socket noise is the point of the chaos arm

        self.httpd = Server(("127.0.0.1", port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name=f"stub-pod-{self.name}")
        self._thread.start()

    def serve_one(self, req: dict, toks: list) -> tuple:
        with self._state_lock:
            if self.inflight >= self.slots + self.queue_limit:
                self.rejected += 1
                return 503, {"error": "queue full"}, {"Retry-After": "1"}
            self.inflight += 1
        try:
            with self._slots_sem:  # slot-bounded service
                matched = 0
                with self._tree_lock:
                    full, partial = self.tree.match(toks, len(toks))
                    matched = len(full) * self.block_size + (
                        partial[1] if partial else 0)
                    n_full = len(toks) // self.block_size
                    if n_full > len(full):
                        # block ids are inert in the stub (no device
                        # pool): absolute positions serve as ids
                        self.tree.insert(full, toks,
                                         list(range(n_full)))
                with self._state_lock:
                    self.requests += 1
                    if matched >= self.block_size:
                        self.prefix_hits += 1
                        self.prefix_tokens_saved += matched
                max_new = int(req.get("max_new_tokens")
                              or self.max_new_default)
                seed = int(req.get("seed") or 0)
                # the "device work": prefill the unshared prompt tail,
                # then decode — wall time scales down with prefix reuse
                # and up with tokens, the real engine's cost shape
                time.sleep((len(toks) - matched)
                           * self.per_prefill_token_s
                           + max_new * self.per_token_s)
                out = self.generate_tokens(toks, seed, max_new)
                with self._state_lock:
                    self.tokens_total += len(out)
                return 200, {"tokens": out}, {}
        finally:
            with self._state_lock:
                self.inflight -= 1

    def metrics_text(self) -> str:
        with self._state_lock:
            return (
                "# TYPE serve_tokens_total counter\n"
                f"serve_tokens_total {self.tokens_total}\n"
                "# TYPE serve_queue_depth gauge\n"
                f"serve_queue_depth {max(0, self.inflight - self.slots)}\n"
                "# TYPE serve_prefix_hits_total counter\n"
                f"serve_prefix_hits_total {self.prefix_hits}\n"
                "# TYPE serve_rejected_total counter\n"
                f"serve_rejected_total {self.rejected}\n"
            )

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def kill(self) -> None:
        """Hard pod death: listener AND every live connection drop (a
        real pod's keep-alive sockets die with it); the port stays
        reserved for restart()."""
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None
            with self._state_lock:
                socks, self._socks = self._socks, set()
            import socket as socket_mod

            for s in socks:
                try:
                    s.shutdown(socket_mod.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            self._thread.join(timeout=5)

    def restart(self) -> None:
        """The pod comes back on the SAME address (a restarted container
        behind a stable service endpoint)."""
        if self.httpd is None:
            self._start(port=self.port)

    def stop(self) -> None:
        self.kill()


def _router_workload(clients: int, requests_per_client: int,
                     block_size: int, templates: int = 16,
                     shared_frac: float = 0.8,
                     template_blocks: int = 4) -> list:
    """The 80%-shared template mix, deterministic per (client, i): a
    shared request is one of ``templates`` template prefixes (each
    ``template_blocks`` FULL blocks long — block-aligned by
    construction) plus a short unique tail; the rest are fully unique
    prompts of the same length.  Returns [per-client list of (tokens,
    seed)]."""
    tlen = template_blocks * block_size
    out = []
    for rank in range(clients):
        reqs = []
        for i in range(requests_per_client):
            shared = ((rank * 37 + i * 11) % 100) < round(
                shared_frac * 100)
            if shared:
                tid = (rank + i) % templates
                prompt = [(tid * 13 + j * 5 + 3) % 256
                          for j in range(tlen)]
                prompt += [(rank * 17 + i * 13 + j) % 256
                           for j in range(3)]  # tail < 1 block
            else:
                prompt = [(rank * 41 + i * 97 + j * 7 + 11) % 256
                          for j in range(tlen + 3)]
            reqs.append((prompt, rank * 1000 + i))
        out.append(reqs)
    return out


def _router_closed_loop(url: str, workload: list, max_new: int,
                        duration_s: float | None = None) -> dict:
    """Closed-loop clients against one router URL: each client replays
    its request list (cycling while ``duration_s`` says to keep going),
    one keep-alive connection per client.  Returns latencies, tokens,
    errors, and each request's (payload, response) for identity spot
    checks."""
    import http.client
    import threading
    from urllib.parse import urlsplit

    netloc = urlsplit(url).netloc
    lock = threading.Lock()
    lat: list[float] = []
    errors: list[str] = []
    tokens = [0]
    requests_done = [0]
    completions: list[tuple[float, int]] = []  # (done_ts, tokens)
    barrier = threading.Barrier(len(workload) + 1)

    def client(rank: int) -> None:
        conn = http.client.HTTPConnection(netloc, timeout=60)
        barrier.wait()
        time.sleep(rank * 0.003)  # desynchronize (bench_serve rationale)
        deadline = (time.monotonic() + duration_s
                    if duration_s is not None else None)
        try:
            i = 0
            while True:
                if deadline is None and i >= len(workload[rank]):
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                toks, seed = workload[rank][i % len(workload[rank])]
                body = json.dumps({"tokens": toks, "seed": seed,
                                   "max_new_tokens": max_new}).encode()
                t0 = time.monotonic()
                try:
                    conn.request("POST", "/v1/generate", body=body,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    out = json.loads(resp.read())
                    if resp.status != 200:
                        raise RuntimeError(f"HTTP {resp.status}: {out}")
                except Exception as e:  # noqa: BLE001 - count, don't crash
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")
                    conn.close()
                    conn = http.client.HTTPConnection(netloc, timeout=60)
                    i += 1
                    continue
                t1 = time.monotonic()
                with lock:
                    lat.append(t1 - t0)
                    tokens[0] += len(out["tokens"])
                    requests_done[0] += 1
                    completions.append((t1, len(out["tokens"])))
                i += 1
        finally:
            conn.close()

    threads = [threading.Thread(target=client, args=(r,), daemon=True)
               for r in range(len(workload))]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    lat.sort()
    # steady-window throughput: the middle of the run, after the ramp
    # and before the drain tail (a fixed-request closed loop loses
    # concurrency as early-finishing clients stop; the ratio the scale
    # assertion wants is between FULLY-LOADED fleets, not tails)
    steady = None
    if completions:
        lo, hi = t0 + 0.15 * wall, t0 + 0.85 * wall
        in_win = [(ts, n) for ts, n in completions if lo <= ts <= hi]
        if in_win and hi > lo:
            steady = round(sum(n for _ts, n in in_win) / (hi - lo), 1)
    return {
        "requests": requests_done[0],
        "errors": errors,
        "wall_s": round(wall, 3),
        "tokens": tokens[0],
        "tokens_per_s": round(tokens[0] / max(wall, 1e-9), 1),
        "tokens_per_s_steady": steady,
        "latency_p50_s": round(_quantile(lat, 0.50), 4) if lat else None,
        "latency_p99_s": round(_quantile(lat, 0.99), 4) if lat else None,
    }


def _router_arm(n_pods: int, policy: str, workload: list, *,
                block_size: int, max_new: int, retry_budget: int = 2
                ) -> dict:
    """One measured arm: fresh stub pods (cold prefix trees), a fresh
    router at ``policy``, the closed-loop workload, then the fleet-level
    prefix stats read back from the pods themselves."""
    from k8s_tpu import router as router_mod

    pods = [_StubServePod(f"pod-{i}", block_size=block_size)
            for i in range(n_pods)]
    targets = [(p.name, p.url) for p in pods]
    router = router_mod.Router(lambda: targets, policy=policy,
                               block_size=block_size,
                               retry_budget=retry_budget,
                               refresh_interval_s=0.2)
    server = router_mod.RouterServer(router)
    server.start()
    try:
        run = _router_closed_loop(f"http://127.0.0.1:{server.port}",
                                  workload, max_new)
        hits = sum(p.prefix_hits for p in pods)
        reqs = sum(p.requests for p in pods)
        run.update({
            "pods": n_pods,
            "policy": policy,
            "fleet_prefix_hits": hits,
            "fleet_prefix_hit_rate": round(hits / max(1, reqs), 3),
            "prefix_tokens_saved": sum(p.prefix_tokens_saved
                                       for p in pods),
            "per_pod_requests": {p.name: p.requests for p in pods},
            "router_counters": router.counters(),
        })
        return run
    finally:
        server.stop()
        for p in pods:
            p.stop()


class _FakeAutoscalePlane:
    """A fleet-plane stand-in for the autoscale ledger phase: settable
    queue/occupancy gauges, no SLO breach."""

    def __init__(self):
        self.queue_mean = 0.0
        self.occupancy_mean = 0.0
        plane = self

        class _Agg:
            def gauge_stats(self, job, family, labels=()):
                del job, labels
                if family == "serve_queue_depth":
                    return {"mean": plane.queue_mean,
                            "max": plane.queue_mean, "sum": 0, "pods": 1}
                if family == "serve_batch_occupancy":
                    return {"mean": plane.occupancy_mean,
                            "max": plane.occupancy_mean, "sum": 0,
                            "pods": 1}
                return None

        class _Slo:
            def breached(self, job):
                del job
                return False

        self.aggregator = _Agg()
        self.slo = _Slo()


def _router_autoscale_ledger_phase(chips_per_replica: int = 4) -> dict:
    """The gang-atomicity proof, against a REAL GangScheduler with a
    full chip ledger: a wanted scale-up parks Queued (zero applies, the
    reservation untouched — never partially placed) until chips free,
    then admits atomically; scale-down drains through the router hook
    BEFORE the apply that shrinks the reservation.  Raises on
    violation; returns the phase record."""
    from k8s_tpu import router as router_mod
    from k8s_tpu import scheduler as scheduler_mod

    job = "bench/serve-fleet"
    sched = scheduler_mod.GangScheduler(total_chips=2 * chips_per_replica)
    d = sched.sync_admit(job, 2 * chips_per_replica, 0, "default")
    assert d.admitted, d.reason
    plane = _FakeAutoscalePlane()
    current = [2]
    order: list[str] = []

    def reserve_fn(j, target):
        return sched.resize(j, target * chips_per_replica).admitted

    def apply_fn(j, target):
        order.append(f"apply:{target}")
        current[0] = target
        # the controller's sync resizes the reservation after a patch;
        # mirror the shrink half here (the grow half was reserve_fn)
        if target * chips_per_replica < (sched.reserved_chips(j) or 0):
            sched.resize(j, target * chips_per_replica)
        return True

    def drain_fn(j, n):
        del j
        order.append(f"drain:{n}")
        return True

    autoscaler = router_mod.Autoscaler(
        lambda: plane, up_queue_depth=4.0, down_queue_depth=0.5,
        hold_evals=2, cooldown_s=30.0)
    loop = router_mod.AutoscaleLoop(
        autoscaler, lambda: [(job, current[0], 1, 4)], apply_fn,
        reserve_fn=reserve_fn, drain_fn=drain_fn)

    failures: list[str] = []
    now = 1000.0
    plane.queue_mean = 10.0  # sustained pressure
    loop.tick_once(now=now)             # hysteresis tick 1: hold
    loop.tick_once(now=now + 1)         # tick 2: up -> resize DENIED
    parked = autoscaler.parked_target(job)
    if current[0] != 2 or loop.applied:
        failures.append(
            f"full ledger: scale-up applied anyway (replicas "
            f"{current[0]}, applied {loop.applied}) — partial placement")
    if parked != 3:
        failures.append(f"scale-up not parked (parked={parked})")
    if sched.reserved_chips(job) != 2 * chips_per_replica:
        failures.append(
            f"reservation moved under a denied resize: "
            f"{sched.reserved_chips(job)}")
    # chips free -> the parked target admits atomically
    sched.set_total(4 * chips_per_replica)
    loop.tick_once(now=now + 2)
    if current[0] != 3:
        failures.append(
            f"freed chips did not un-park the scale-up (replicas "
            f"{current[0]})")
    if sched.reserved_chips(job) != 3 * chips_per_replica:
        failures.append(
            f"reservation not grown atomically: "
            f"{sched.reserved_chips(job)} != {3 * chips_per_replica}")
    # idle -> scale-down drains BEFORE the apply releases chips
    plane.queue_mean = 0.0
    loop.tick_once(now=now + 100)       # past cooldown; streak 1
    loop.tick_once(now=now + 101)       # streak 2 -> down
    down_events = [e for e in order if e.startswith(("drain", "apply:2"))]
    if down_events[:2] != ["drain:1", "apply:2"]:
        failures.append(
            f"scale-down order wrong (drain must precede apply): {order}")
    if sched.reserved_chips(job) != 2 * chips_per_replica:
        failures.append(
            f"scale-down did not free the victim's chips: "
            f"{sched.reserved_chips(job)}")
    if failures:
        raise RuntimeError("; ".join(failures))
    return {"order": order, "final_replicas": current[0],
            "final_chips": sched.reserved_chips(job),
            "parked_then_admitted": True}


def bench_router(pods: int = 4, clients: int = 16,
                 requests_per_client: int = 16, block_size: int = 8,
                 shared_frac: float = 0.8, max_new: int = 24,
                 slo_p99_s: float = 0.75,
                 kill_run_s: float = 4.5) -> dict:
    """The --router scenario (ISSUE 13), EMBEDDED assertions throughout
    (this bench is the acceptance proof of the front door, not advisory
    trend data):

    - **near-linear scale-out**: aggregate tokens/s behind the router at
      ``pods`` pods >= 0.7 x pods x the 1-pod figure (same closed-loop
      clients, same 80%-shared template mix);
    - **affinity is a fleet asset**: fleet-level prefix hit rate under
      affine routing >= the single-pod hit rate (the per-pod caches
      compose instead of fragmenting), with the measured uplift vs a
      ``random`` placement arm reported AND asserted positive;
    - **fixed-seed identity**: the same (prompt, seed) answered through
      the router matches a direct pod call byte-for-byte;
    - **kill/rejoin under SLO**: a pod hard-killed mid-run is health-
      evicted (zero client-visible errors — transport failures retry
      against the next ring candidate), rejoins after restart, and p99
      stays under ``slo_p99_s`` across the whole incident;
    - **gang-atomic autoscale**: against a real GangScheduler with a
      full ledger, a wanted scale-up parks (zero applies, reservation
      untouched) until chips free, then admits atomically; scale-down
      drains through the router hook before chips release.
    """
    from k8s_tpu import router as router_mod

    failures: list[str] = []
    workload = _router_workload(clients, requests_per_client, block_size,
                                shared_frac=shared_frac)

    # -- scale + affinity arms -------------------------------------------
    single = _router_arm(1, router_mod.POLICY_AFFINE, workload,
                         block_size=block_size, max_new=max_new)
    affine = _router_arm(pods, router_mod.POLICY_AFFINE, workload,
                         block_size=block_size, max_new=max_new)
    randomized = _router_arm(pods, router_mod.POLICY_RANDOM, workload,
                             block_size=block_size, max_new=max_new)
    for arm in (single, affine, randomized):
        if arm["errors"]:
            failures.append(
                f"arm pods={arm['pods']} policy={arm['policy']}: request "
                f"errors {arm['errors'][:3]}")
    scaling = ((affine["tokens_per_s_steady"]
                or affine["tokens_per_s"])
               / max(single["tokens_per_s_steady"]
                     or single["tokens_per_s"], 1e-9))
    if scaling < 0.7 * pods:
        failures.append(
            f"aggregate tokens/s not near-linear: {pods} pods gave "
            f"{scaling:.2f}x one pod (< {0.7 * pods:.1f}x bound)")
    hit_uplift = (affine["fleet_prefix_hit_rate"]
                  - randomized["fleet_prefix_hit_rate"])
    if affine["fleet_prefix_hit_rate"] < \
            single["fleet_prefix_hit_rate"] - 0.05:
        failures.append(
            f"affine fleet hit rate {affine['fleet_prefix_hit_rate']} "
            f"fell below the single-pod baseline "
            f"{single['fleet_prefix_hit_rate']}: affinity is "
            "fragmenting the shared templates across pods")
    if hit_uplift <= 0.05:
        failures.append(
            f"affine routing shows no prefix-hit uplift vs random "
            f"({affine['fleet_prefix_hit_rate']} vs "
            f"{randomized['fleet_prefix_hit_rate']})")

    # -- fixed-seed identity through the router vs direct ----------------
    probe_prompt, probe_seed = workload[0][0]
    direct = _StubServePod.generate_tokens(probe_prompt, probe_seed,
                                           max_new)
    pod = _StubServePod("probe-pod", block_size=block_size)
    router = router_mod.Router(lambda: [(pod.name, pod.url)],
                               block_size=block_size)
    server = router_mod.RouterServer(router)
    server.start()
    try:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/generate",
            data=json.dumps({"tokens": probe_prompt, "seed": probe_seed,
                             "max_new_tokens": max_new}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:
            routed = json.loads(resp.read())["tokens"]
    finally:
        server.stop()
        pod.stop()
    if routed != direct:
        failures.append(
            "fixed-seed output through the router differs from the "
            "direct pod call: the proxy is not transparent")

    # -- pod kill + rejoin under SLO -------------------------------------
    kill_pods = [_StubServePod(f"kp-{i}", block_size=block_size)
                 for i in range(pods)]
    targets = [(p.name, p.url) for p in kill_pods]
    router = router_mod.Router(lambda: targets,
                               policy=router_mod.POLICY_AFFINE,
                               block_size=block_size,
                               refresh_interval_s=0.1,
                               fail_threshold=1, probe_timeout_s=0.2,
                               request_timeout_s=10.0)
    server = router_mod.RouterServer(router)
    server.start()
    victim = kill_pods[-1]
    incident: dict = {}

    def _chaos():
        time.sleep(kill_run_s / 3)
        victim.kill()
        incident["killed_at"] = time.monotonic()
        # observe the health eviction (fail_threshold=1 + the 0.1s
        # refresh loop probing): the victim must leave the ring
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            state = {b["name"]: b for b in router.backends()}
            if not state[victim.name]["healthy"]:
                incident["evicted_s"] = round(
                    time.monotonic() - incident["killed_at"], 3)
                break
            time.sleep(0.02)
        time.sleep(kill_run_s / 3)
        victim.restart()
        incident["requests_at_rejoin"] = victim.requests

    import threading as _threading

    chaos = _threading.Thread(target=_chaos, daemon=True)
    chaos.start()
    kill_run = _router_closed_loop(
        f"http://127.0.0.1:{server.port}", workload, max_new,
        duration_s=kill_run_s)
    chaos.join(timeout=10)
    rejoined = {b["name"]: b for b in router.backends()}.get(
        victim.name, {})
    victim_post_rejoin = victim.requests - incident.get(
        "requests_at_rejoin", 0)
    server.stop()
    for p in kill_pods:
        p.stop()
    if kill_run["errors"]:
        failures.append(
            f"{len(kill_run['errors'])} request(s) lost across the pod "
            f"kill (first: {kill_run['errors'][:2]}) — transport "
            "failures must retry against the next ring candidate")
    if "evicted_s" not in incident:
        failures.append("dead pod was never health-evicted from the ring")
    if kill_run["latency_p99_s"] is not None \
            and kill_run["latency_p99_s"] > slo_p99_s:
        failures.append(
            f"p99 {kill_run['latency_p99_s']}s breached the "
            f"{slo_p99_s}s SLO across the kill/rejoin incident")
    if not rejoined.get("healthy"):
        failures.append("restarted pod was not re-admitted to the ring")
    elif victim_post_rejoin <= 0:
        failures.append(
            "restarted pod took no traffic after rejoining the ring")

    # -- gang-atomic autoscale against a full ledger ---------------------
    try:
        autoscale_phase = _router_autoscale_ledger_phase()
    except RuntimeError as e:
        autoscale_phase = {"error": str(e)}
        failures.append(f"autoscale ledger phase: {e}")

    result = {
        "pods": pods,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "block_size": block_size,
        "shared_frac": shared_frac,
        "single_pod": single,
        "affine": affine,
        "random": randomized,
        "scaling_x": round(scaling, 2),
        "scaling_bound_x": round(0.7 * pods, 2),
        "affine_hit_rate": affine["fleet_prefix_hit_rate"],
        "single_pod_hit_rate": single["fleet_prefix_hit_rate"],
        "random_hit_rate": randomized["fleet_prefix_hit_rate"],
        "affine_hit_uplift_vs_random": round(hit_uplift, 3),
        "fixed_seed_identity_ok": routed == direct,
        "kill_rejoin": {**kill_run, **incident,
                        "victim_requests_after_rejoin":
                        victim_post_rejoin,
                        "slo_p99_s": slo_p99_s},
        "autoscale": autoscale_phase,
    }
    if failures:
        result["failures"] = failures
        err = RuntimeError("router bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def run_router(args) -> dict:
    """The --router scenario wrapper (bench.py contract: one JSON-able
    dict with a metric/value/unit headline).  The artifact is written on
    failure too — with a ``failures`` field — like bench_fleet.json."""
    try:
        r = bench_router(
            pods=args.router_pods,
            clients=args.router_clients,
            requests_per_client=args.router_requests,
            shared_frac=args.router_shared_frac,
        )
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write_artifact(args.router_out, {
                "metric": "router_affine_hit_uplift",
                "value": partial.get("affine_hit_uplift_vs_random"),
                "unit": "hit_rate_delta",
                **partial,
            })
        raise
    out = {
        "metric": "router_affine_hit_uplift",
        "value": r["affine_hit_uplift_vs_random"],
        "unit": "hit_rate_delta",
        **r,
    }
    _write_artifact(args.router_out, out)
    return out


def _noop_ctx():
    import contextlib

    return contextlib.nullcontext()


def untraced():
    """Context manager suppressing span recording for a bench segment.

    The serial-baseline rounds exist to be *compared against*, not to be
    profiled: letting their O(replicas x RTT) create waves land in the
    same ring buffer would fold baseline latencies into the --trace
    stage table and misreport where the parallel path spends time.
    """
    import contextlib

    @contextlib.contextmanager
    def _cm():
        from k8s_tpu import trace

        old = trace.TRACER.sample_rate
        trace.TRACER.sample_rate = 0.0
        try:
            yield
        finally:
            trace.TRACER.sample_rate = old

    return _cm()


def trace_stage_breakdown() -> dict:
    """Per-stage p50/p99 latency breakdown over every span in the tracing
    ring buffer, grouped by span name — the "where did the sync go" table
    for a --trace bench run.

    FAIL-SOFT by contract (ci_config.yaml bench_smoke runs non-gating):
    any failure to assemble the breakdown — tracing import broken, empty
    buffer, malformed trace dicts — degrades to a ``trace_error`` key in
    the JSON line instead of failing the bench.
    """
    try:
        from k8s_tpu import trace

        by_stage: dict[str, list[float]] = {}
        stack = list(trace.debug_traces(limit=1_000_000))
        while stack:
            span = stack.pop()
            by_stage.setdefault(span["name"], []).append(span["duration_ms"])
            stack.extend(span.get("children") or [])
        if not by_stage:
            return {"trace_error": "no traces captured"}
        stages = {}
        for name, vals in sorted(by_stage.items()):
            vals.sort()
            stages[name] = {
                "count": len(vals),
                "p50_ms": round(_quantile(vals, 0.50), 3),
                "p99_ms": round(_quantile(vals, 0.99), 3),
            }
        return {"stages": stages}
    except Exception as e:  # noqa: BLE001 - advisory data must not gate
        return {"trace_error": f"{type(e).__name__}: {e}"}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--jobs", type=int, default=20)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--threadiness", type=int, default=1,
                   help="controller worker threads (operator --threadiness)")
    p.add_argument("--resync", type=float, default=5.0,
                   help="informer resync period seconds (reference: 30)")
    p.add_argument("--backend", choices=["fake", "rest"], default="fake",
                   help="fake = in-process store; rest = full HTTP wire "
                   "protocol through the apiserver fixture")
    p.add_argument("--slice-scale", action="store_true",
                   help="run the slice-scale fan-out scenario (1 job x "
                   "--slice-replicas workers, serial vs parallel creation, "
                   "plus the --jobs x --replicas time-to-ready comparison) "
                   "and emit one JSON line")
    p.add_argument("--slice-replicas", type=int, default=256,
                   help="gang size for the 1-job slice-scale scenario")
    p.add_argument("--create-latency", type=float, default=None,
                   help="injected per-create RTT seconds (fake backend only; "
                   "default 0.01 under --slice-scale, 0 otherwise)")
    p.add_argument("--create-concurrency", type=int, default=None,
                   help="pin the controller's creation fan-out width "
                   "(1 = fully serial legacy path; default: "
                   "K8S_TPU_CREATE_CONCURRENCY or 16)")
    p.add_argument("--slice-rounds", type=int, default=3,
                   help="parallel-path rounds for p50/p99 sync latency")
    p.add_argument("--measure-restart", action="store_true",
                   help="run the gang-restart teardown scenario (1 TPU gang "
                   "x --slice-replicas, fail one member retryably, measure "
                   "kill-to-all-Running at parallel vs serial teardown "
                   "under --delete-latency) and emit one JSON line; "
                   "combinable with --slice-scale (two lines)")
    p.add_argument("--delete-latency", type=float, default=None,
                   help="injected per-delete RTT seconds (fake backend "
                   "only; default 0.01 under --measure-restart)")
    p.add_argument("--delete-concurrency", type=int, default=None,
                   help="pin the controller's teardown fan-out width "
                   "(1 = fully serial legacy path; default: "
                   "K8S_TPU_DELETE_CONCURRENCY, falling back to "
                   "K8S_TPU_CREATE_CONCURRENCY, then 16)")
    p.add_argument("--restart-rounds", type=int, default=3,
                   help="parallel-teardown kill-to-running samples for p50")
    p.add_argument("--contention", action="store_true",
                   help="run the gang-admission contention scenario "
                   "(--contention-jobs low-priority TPU gangs racing for a "
                   "cluster that fits one gang, then a high-priority "
                   "arrival preempting mid-backlog; measures admission "
                   "latency, chip utilization, and preemption turnaround) "
                   "and emit one JSON line; combinable with the other "
                   "scenarios")
    p.add_argument("--contention-jobs", type=int, default=4,
                   help="low-priority gangs racing for the slice (>= 2)")
    p.add_argument("--contention-replicas", type=int, default=4,
                   help="hosts per contention gang")
    p.add_argument("--contention-priority", type=int, default=10,
                   help="priority of the late-arriving preemptor job")
    p.add_argument("--contention-runtime", type=float, default=0.5,
                   help="synthetic per-job runtime seconds")
    p.add_argument("--contention-chips", type=int, default=None,
                   help="total cluster chips (default: exactly one gang's "
                   "worth, so jobs strictly serialize)")
    p.add_argument("--serve", action="store_true",
                   help="run the continuous-batching serving bench "
                   "(harness/bench_serve.py: N closed-loop HTTP clients "
                   "vs the tiny-model inference server, single-flight vs "
                   "batched tokens/s + p50/p99 latency) and emit one JSON "
                   "line; combinable with the other scenarios")
    p.add_argument("--serve-concurrency", type=int, default=8,
                   help="closed-loop client threads for --serve")
    p.add_argument("--serve-slots", type=int, default=8,
                   help="decode slots for the batched --serve phase")
    p.add_argument("--serve-requests", type=int, default=4,
                   help="requests per client per --serve phase")
    p.add_argument("--serve-max-new-short", type=int, default=32)
    p.add_argument("--serve-max-new-long", type=int, default=96)
    p.add_argument("--serve-sampled", type=int, choices=(0, 1),
                   default=1,
                   help="include the shared-prefix temperature>0 phases "
                   "in --serve: exclusive-lane sampling vs the batched "
                   "sampling lane with radix prefix-cache reuse "
                   "(compile counts + hit rate land in the JSON "
                   "artifact)")
    p.add_argument("--serve-shared-frac", type=float, default=0.8,
                   help="fraction of sampled-phase requests sharing the "
                   "templated prompt prefix")
    p.add_argument("--serve-spec", type=int, choices=(0, 1), default=1,
                   help="include the speculative phases in --serve: "
                   "exclusive-lane vs batched variable-width speculation "
                   "over structured prompts (spec_batched >= 1.5x "
                   "spec_exclusive asserted; acceptance rate + compile "
                   "counts in the JSON artifact)")
    p.add_argument("--serve-draft-k", type=int, default=4,
                   help="speculative draft chunk width for the --serve "
                   "spec phases")
    p.add_argument("--requests-audit-out", default=None,
                   help="write the --serve phases' request-recorder "
                   "audit (per-phase TTFT/TPOT/queue-wait percentiles, "
                   "dominant-phase counts, engine step-ledger rollups, "
                   "slowest timelines) as a requests_audit.json "
                   "artifact — written on failed runs too (ISSUE 12)")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated prefill/decode serving scenario "
                   "(ISSUE 15): two real engines per arm behind the "
                   "real router, long-prompt storms migrating KV block "
                   "chains to the decode tier over real sockets — "
                   "decode p99 stays flat on the split topology while "
                   "the collapsed baseline convoys; fixed-seed "
                   "migrated-vs-local identity embedded")
    p.add_argument("--disagg-shorts", type=int, default=4,
                   help="closed-loop short-decode clients (their p99 "
                   "is the metric)")
    p.add_argument("--disagg-longs", type=int, default=3,
                   help="long-prompt storm clients at 1x (storm2x "
                   "doubles this)")
    p.add_argument("--disagg-duration", type=float, default=4.0,
                   help="seconds per measured phase")
    p.add_argument("--disagg-long-len", type=int, default=112,
                   help="long-prompt token length")
    p.add_argument("--disagg-out", default=None,
                   help="write the bench_disagg.json artifact here "
                   "(written on assertion failure too)")
    p.add_argument("--kvtier", action="store_true",
                   help="tiered KV memory hierarchy scenario (ISSUE "
                   "17): host-RAM spill tier vs evict-recompute on a "
                   "corpus ~10x pool capacity (tokens/s + post-warmup "
                   "prefix hit rate must strictly beat the baseline), "
                   "fingerprint-dedup migration storm (wire bytes "
                   "saved > 0), and fixed-seed identity through "
                   "demote->promote and deduped migration on every "
                   "lane — greedy/sampled/top-k/spec")
    p.add_argument("--kvtier-corpus", type=int, default=24,
                   help="distinct prompts in the spill replay corpus "
                   "(~10x the pool's prefix headroom at the default "
                   "geometry)")
    p.add_argument("--kvtier-rounds", type=int, default=3,
                   help="measured post-warmup replay rounds per arm")
    p.add_argument("--kvtier-spill-mb", type=int, default=16,
                   help="host spill budget for the spill arm")
    p.add_argument("--kvtier-storm", type=int, default=6,
                   help="repeated-prefix migrations in the dedup storm")
    p.add_argument("--kvtier-out", default=None,
                   help="write the bench_kvtier.json artifact here "
                   "(written on assertion failure too)")
    p.add_argument("--serve-mp", action="store_true",
                   help="multi-host tensor-parallel serving gang bench "
                   "(harness/bench_serve_mp.py: 1-process vs N-process "
                   "CPU mesh, token-identity + mesh-overhead + "
                   "per-process compile-budget assertions embedded; "
                   "ISSUE 14)")
    p.add_argument("--serve-mp-processes", type=int, default=4,
                   help="mesh size for --serve-mp")
    p.add_argument("--serve-mp-requests", type=int, default=24,
                   help="requests in the --serve-mp timed script")
    p.add_argument("--serve-mp-slots", type=int, default=8,
                   help="decode slots for --serve-mp")
    p.add_argument("--serve-mp-threads", type=int, default=10,
                   help="closed-loop submitters for --serve-mp")
    p.add_argument("--serve-mp-out", default=None,
                   help="also write the --serve-mp JSON artifact to "
                   "this path (written on failure too, failures field "
                   "included)")
    p.add_argument("--serve-out", default=None,
                   help="also write the --serve JSON result to this path "
                   "(bench artifact)")
    p.add_argument("--churn", action="store_true",
                   help="run the churn-at-scale scenario (ISSUE 7): "
                   "--churn-jobs concurrent TFJobs through a create storm, "
                   "steady-state windows at N/2 and N jobs, and a "
                   "fail/restart storm, measured through the flight "
                   "recorder; EMBEDDED ASSERTIONS (steady apiserver "
                   "calls/sec flat vs job count, zero steady-state LISTs, "
                   "churn cost bounded per event, relists at the expected "
                   "count, sync p99 store-bound) fail the bench; emits one "
                   "JSON line with the {verb,resource} call breakdown and "
                   "timeline depth stats; combinable with other scenarios")
    p.add_argument("--churn-jobs", type=int, default=2000,
                   help="concurrent TFJobs for --churn (the scale proof "
                   "target is 2000-5000)")
    p.add_argument("--churn-replicas", type=int, default=1,
                   help="TPU replicas per churn job")
    p.add_argument("--churn-fail-frac", type=float, default=0.05,
                   help="fraction of jobs whose gang is failed in the "
                   "churn storm")
    p.add_argument("--churn-steady", type=float, default=2.0,
                   help="seconds per steady-state measurement window")
    p.add_argument("--churn-resync", type=float, default=1.0,
                   help="informer resync period for --churn (every job "
                   "resyncs each period; proves steady syncs do zero "
                   "apiserver calls)")
    p.add_argument("--churn-threadiness", type=int, default=4,
                   help="controller worker threads for --churn")
    p.add_argument("--churn-out", default=None,
                   help="also write the --churn JSON result to this path "
                   "(bench artifact)")
    p.add_argument("--fleet", action="store_true",
                   help="run the fleet-telemetry scenario (ISSUE 8): "
                   "--fleet-jobs serving TFJobs totalling --fleet-pods "
                   "fake serving pods scraped by the controller's fleet "
                   "plane; EMBEDDED ASSERTIONS (per-job aggregated "
                   "counter rates match the known per-pod truth, fleet "
                   "p99 from merged histograms matches the closed-form "
                   "reference, steady-state scraping adds zero apiserver "
                   "calls, an injected latency breach flips the burn-rate "
                   "rule within two scrape intervals and lands a timeline "
                   "event + K8s Event, zero scrape failures) fail the "
                   "bench; emits one JSON line; combinable with other "
                   "scenarios")
    p.add_argument("--fleet-pods", type=int, default=32,
                   help="total fake serving pods for --fleet (the "
                   "acceptance floor is 32)")
    p.add_argument("--fleet-jobs", type=int, default=4,
                   help="serving TFJobs the pods are split across")
    p.add_argument("--fleet-interval", type=float, default=0.25,
                   help="scrape interval seconds for --fleet")
    p.add_argument("--fleet-steady-cycles", type=int, default=8,
                   help="scrape cycles in the zero-apiserver-call window")
    p.add_argument("--fleet-out", default=None,
                   help="also write the --fleet JSON result to this path "
                   "(bench artifact)")
    p.add_argument("--router", action="store_true",
                   help="run the serving front-door scenario (ISSUE 13): "
                   "closed-loop clients vs 1 -> --router-pods stub "
                   "serving pods (real radix PrefixTrees, slot-bounded "
                   "service, 503 shedding) behind the prefix-affine "
                   "router; EMBEDDED ASSERTIONS (near-linear aggregate "
                   "tokens/s, affine fleet prefix-hit-rate >= the "
                   "single-pod baseline with measured uplift vs a "
                   "--router-policy random arm, fixed-seed identity "
                   "through the router, zero lost requests + p99 under "
                   "SLO across a pod kill/rejoin, and gang-atomic "
                   "autoscale against a full chip ledger: parked Queued "
                   "never partial, drain before chip release) fail the "
                   "bench; emits one JSON line; combinable with other "
                   "scenarios")
    p.add_argument("--router-pods", type=int, default=4,
                   help="stub serving pods in the scale-out arm (the "
                   "1-pod baseline always runs)")
    p.add_argument("--router-clients", type=int, default=16,
                   help="closed-loop client threads per --router arm")
    p.add_argument("--router-requests", type=int, default=16,
                   help="requests per client per --router arm")
    p.add_argument("--router-shared-frac", type=float, default=0.8,
                   help="fraction of --router requests sharing a "
                   "templated block-aligned prompt prefix")
    p.add_argument("--router-out", default=None,
                   help="also write the --router JSON result to this "
                   "path (bench artifact)")
    p.add_argument("--lock-audit-out", default=None,
                   help="enable the runtime lock checker "
                   "(K8S_TPU_LOCK_CHECK=1; k8s_tpu.analysis.checkedlock) "
                   "for the whole bench run and write the lock_audit.json "
                   "artifact — acquisition DAG aggregated by lock name, "
                   "per-lock contention counts and max hold times, "
                   "watchdog/cycle violation records — to this path; a "
                   "cycle violation raises inside the offending scenario "
                   "(the JSON still records it)")
    p.add_argument("--compile-audit-out", default=None,
                   help="enable the runtime XLA compile ledger "
                   "(K8S_TPU_COMPILE_LEDGER=1; "
                   "k8s_tpu.analysis.compileledger) for the whole bench "
                   "run and write the compile_audit.json artifact — "
                   "per-seam budgets, per-fingerprint compile counts/"
                   "durations/origin stacks, the recent-event ring — to "
                   "this path; a seam recompiling past its declared "
                   "budget raises CompileBudgetExceeded inside the "
                   "offending scenario (the JSON still records it)")
    p.add_argument("--trace", action="store_true",
                   help="force tracing on (sample rate 1.0) and append a "
                   "per-stage p50/p99 breakdown ('stages') to the JSON "
                   "line; serial-baseline segments run untraced so the "
                   "table reflects the parallel path only, and breakdown "
                   "assembly is fail-soft (a 'trace_error' key, never a "
                   "nonzero exit)")
    args = p.parse_args(argv)

    old_lock_check = os.environ.get("K8S_TPU_LOCK_CHECK")
    if args.lock_audit_out:
        # before any scenario constructs a cluster/engine: the checkedlock
        # factories read the env at lock-creation time
        os.environ["K8S_TPU_LOCK_CHECK"] = "1"
    old_compile_ledger = os.environ.get("K8S_TPU_COMPILE_LEDGER")
    if args.compile_audit_out:
        # before the serve scenario constructs its engines: the
        # ledger's maybe_active() reads the env at seam-declaration time
        os.environ["K8S_TPU_COMPILE_LEDGER"] = "1"

    try:
        return _run(args, p)
    finally:
        # the artifacts must land on failed runs too — a cycle/budget
        # violation raising inside a scenario is exactly the run worth
        # auditing
        _write_lock_audit(args)
        _write_compile_audit(args)
        if args.lock_audit_out:
            # in-process callers (tests) must not inherit checker mode
            if old_lock_check is None:
                os.environ.pop("K8S_TPU_LOCK_CHECK", None)
            else:
                os.environ["K8S_TPU_LOCK_CHECK"] = old_lock_check
        if args.compile_audit_out:
            if old_compile_ledger is None:
                os.environ.pop("K8S_TPU_COMPILE_LEDGER", None)
            else:
                os.environ["K8S_TPU_COMPILE_LEDGER"] = old_compile_ledger


def _run(args, p) -> int:
    if args.trace:
        from k8s_tpu import trace

        trace.configure(sample_rate=1.0)

    if args.slice_scale or args.measure_restart or args.contention \
            or args.serve or args.serve_mp or args.churn or args.fleet \
            or args.router or args.disagg or args.kvtier:
        if args.backend != "fake" and (args.slice_scale
                                       or args.measure_restart
                                       or args.contention or args.churn
                                       or args.fleet or args.router):
            p.error("--slice-scale/--measure-restart/--contention/--churn/"
                    "--fleet require --backend fake: the injected RTTs, "
                    "the capacity knob, and the fake serving pods only "
                    "exist on the in-process cluster")
        if args.create_latency is None:
            args.create_latency = 0.01
        if args.delete_latency is None:
            args.delete_latency = 0.01
        results = []
        if args.slice_scale:
            results.append(run_slice_scale(args))
        if args.measure_restart:
            results.append(run_measure_restart(args))
        if args.contention:
            results.append(run_contention(args))
        if args.churn:
            # late operator scenario: it resets the flight counters, so
            # earlier scenarios' accounting must already be consumed
            results.append(run_churn(args))
        if args.fleet:
            # also resets the flight counters (runs after --churn has
            # consumed its own accounting)
            results.append(run_fleet(args))
        if args.router:
            # self-contained: stub pods + in-process router, no cluster
            results.append(run_router(args))
        if args.serve:
            results.append(run_serve(args))
        if args.disagg:
            # real engines + real sockets like --serve; runs after it
            # so the JAX warmup cost is already paid in-process
            results.append(run_disagg(args))
        if args.kvtier:
            # in-process engines + one real socket pair, after --disagg
            # so the JAX warmup cost is already paid in-process
            results.append(run_kvtier(args))
        if args.serve_mp:
            # real OS-process gangs: runs last so the in-process
            # scenarios' timings aren't perturbed by gang spawn load
            results.append(run_serve_mp(args))
        if args.trace:
            # one stage table for the whole invocation, on the last line
            results[-1].update(trace_stage_breakdown())
        for result in results:
            print(json.dumps(result))
        return 0

    if (args.create_latency or args.delete_latency) and args.backend != "fake":
        p.error("--create-latency/--delete-latency only exist on the fake "
                "backend")
    result = bench_time_to_ready(args.jobs, args.replicas, args.timeout,
                                 threadiness=args.threadiness,
                                 resync_period_s=args.resync,
                                 backend_mode=args.backend,
                                 create_delay_s=args.create_latency or 0.0,
                                 create_concurrency=args.create_concurrency,
                                 delete_delay_s=args.delete_latency or 0.0,
                                 delete_concurrency=args.delete_concurrency)
    out = {"metric": "tfjob_time_to_ready_p50",
           "value": result["time_to_ready_p50_s"],
           "unit": "s", "backend": args.backend, **result}
    if args.trace:
        out.update(trace_stage_breakdown())
    print(json.dumps(out))

    from k8s_tpu.client import rest

    if rest.WIRE_PROFILE_ENABLED and args.backend == "rest":
        # K8S_TPU_WIRE_PROFILE=1: the per-verb budget behind the
        # rest-vs-fake ratio (BASELINE.md wire-floor arithmetic)
        profile = rest.wire_profile_snapshot()
        total_calls = sum(v["count"] for v in profile.values())
        total_s = sum(v["seconds"] for v in profile.values())
        # counters are process-wide for the cluster's whole lifetime, so
        # the per-job figure AMORTIZES fixed startup traffic (informer
        # bootstrap LISTs etc.) — negligible at hundreds of jobs, dominant
        # at --jobs 1
        print(json.dumps({
            "metric": "wire_profile",
            "requests_total": total_calls,
            "requests_per_job_amortized": round(total_calls / args.jobs, 1),
            "client_seconds_total": round(total_s, 3),
            "mean_us_per_call": round(1e6 * total_s / max(total_calls, 1)),
            "by_verb": profile,
        }))
    return 0


def _write_lock_audit(args) -> None:
    """Emit the runtime lock checker's lock_audit.json artifact (ISSUE 10)
    plus a one-line JSON summary on stdout, when --lock-audit-out is set."""
    if not getattr(args, "lock_audit_out", None):
        return
    from k8s_tpu.analysis import checkedlock

    snap = checkedlock.write_audit(args.lock_audit_out)
    print(json.dumps({
        "metric": "lock_audit",
        "path": args.lock_audit_out,
        "locks": len(snap["locks"]),
        "edges": len(snap["edges"]),
        "max_hold_s": max(
            [st["max_hold_s"] for st in snap["locks"].values()] or [0.0]),
        "contention_total": sum(
            st["contention"] for st in snap["locks"].values()),
        "watchdog_violations": len(snap["watchdog_violations"]),
        "cycle_violations": snap["cycle_violations"],
    }))


def _write_compile_audit(args) -> None:
    """Emit the runtime compile ledger's compile_audit.json artifact
    (ISSUE 11) plus a one-line JSON summary on stdout, when
    --compile-audit-out is set.  In-process callers (tests) get a clean
    slate afterwards: the process-global ledger is deactivated."""
    if not getattr(args, "compile_audit_out", None):
        return
    from k8s_tpu.analysis import compileledger

    payload = compileledger.write_audit(args.compile_audit_out)
    print(json.dumps({
        "metric": "compile_audit",
        "path": args.compile_audit_out,
        "enabled": payload["enabled"],
        "seams": len(payload["seams"]),
        "total_compiles": payload["total_compiles"],
        "total_programs": payload["total_programs"],
        "over_budget": payload["over_budget"],
    }))
    compileledger.set_active(None)


if __name__ == "__main__":
    sys.exit(main())

"""Declarative workflow/component app dirs — the ksonnet analogue.

The reference declares its CI workflows and deployable test app as ksonnet
component trees (test/workflows/components/workflows.libsonnet:139-344,
test/test-app/components/core.jsonnet:1-5), rendered with ``ks param set`` +
``ks show``/``ks apply``.  Here an *app dir* is plain YAML:

    <app_dir>/params.yaml              # per-component default params
    <app_dir>/components/<name>.yaml   # template(s) with ${param} holes

``render_component`` substitutes params (defaults overridden by ``--params
k=v,...`` — the `ks param set` model) and returns the parsed documents.
Substitution is strict both ways: a ``${hole}`` with no param and an
override naming no declared param are errors, so manifests and params.yaml
cannot drift apart silently.

CLI (mirrors the reference's test_runner/ks usage, py/test_runner.py:239-276):

    python -m k8s_tpu.harness.workflows render --app_dir test/workflows \\
        --component e2e --params name=pr-123,version_tag=abc
    python -m k8s_tpu.harness.workflows run --app_dir test/workflows \\
        --component simple_tfjob --params name=smoke,namespace=default
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import re
import sys

import yaml

log = logging.getLogger(__name__)

_HOLE_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")


class ComponentError(Exception):
    """Bad app dir / component / params."""


def load_params(app_dir: str, component: str) -> dict:
    """Default params for ``component`` from <app_dir>/params.yaml."""
    path = os.path.join(app_dir, "params.yaml")
    try:
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
    except OSError as e:
        raise ComponentError(f"no params.yaml in app dir {app_dir}: {e}") from e
    components = cfg.get("components") or {}
    if component not in components:
        raise ComponentError(
            f"component {component!r} not declared in {path} "
            f"(have: {sorted(components)})"
        )
    return dict(components[component] or {})


def parse_params(spec: str) -> dict:
    """``"k=v,k2=v2"`` → dict (the reference test_runner --params format,
    py/test_runner.py:388-396)."""
    out = {}
    for piece in (spec or "").split(","):
        piece = piece.strip()
        if not piece:
            continue
        if "=" not in piece:
            raise ComponentError(f"bad --params piece {piece!r} (want k=v)")
        k, v = piece.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def _substitute(text: str, params: dict) -> str:
    def repl(m: re.Match) -> str:
        key = m.group(1)
        if key not in params:
            raise ComponentError(
                f"template hole ${{{key}}} has no parameter (declared: "
                f"{sorted(params)})"
            )
        v = params[key]
        return v if isinstance(v, str) else json.dumps(v)

    return _HOLE_RE.sub(repl, text)


def render_component(
    app_dir: str, component: str, overrides: dict | None = None
) -> list[dict]:
    """Render one component to its list of YAML documents."""
    params = load_params(app_dir, component)
    for key in overrides or {}:
        if key not in params:
            raise ComponentError(
                f"override {key!r} names no declared param of {component!r} "
                f"(declared: {sorted(params)})"
            )
    params.update(overrides or {})

    path = os.path.join(app_dir, "components", f"{component}.yaml")
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise ComponentError(f"no such component template: {path}") from e

    docs = [d for d in yaml.safe_load_all(_substitute(text, params)) if d]
    if not docs:
        raise ComponentError(f"component {component!r} rendered no documents")
    return docs


def list_components(app_dir: str) -> list[str]:
    comp_dir = os.path.join(app_dir, "components")
    try:
        names = sorted(
            f[:-5] for f in os.listdir(comp_dir) if f.endswith(".yaml")
        )
    except OSError as e:
        raise ComponentError(f"no components/ dir in {app_dir}: {e}") from e
    return names


def validate_workflow(wf: dict) -> None:
    """Structural checks on an Argo-shaped Workflow: entrypoint/onExit
    resolve, every step references a defined template, no duplicate
    template names, and the step graph is acyclic."""
    if wf.get("kind") != "Workflow":
        raise ComponentError(f"not a Workflow: kind={wf.get('kind')!r}")
    spec = wf.get("spec") or {}
    templates = spec.get("templates") or []
    names = [t.get("name") for t in templates]
    if len(set(names)) != len(names):
        dupes = sorted({n for n in names if names.count(n) > 1})
        raise ComponentError(f"duplicate template names: {dupes}")
    by_name = {t["name"]: t for t in templates}

    for key in ("entrypoint", "onExit"):
        ref = spec.get(key)
        if ref and ref not in by_name:
            raise ComponentError(f"spec.{key}={ref!r} names no template")
    if not spec.get("entrypoint"):
        raise ComponentError("spec.entrypoint is required")

    edges: dict[str, set] = {n: set() for n in by_name}
    for t in templates:
        for group in t.get("steps") or []:
            for step in group:
                ref = step.get("template")
                if ref not in by_name:
                    raise ComponentError(
                        f"step {step.get('name')!r} in template "
                        f"{t['name']!r} references unknown template {ref!r}"
                    )
                edges[t["name"]].add(ref)

    # cycle check (steps templates may nest, e.g. e2e -> sub-steps)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in by_name}

    def visit(n: str, stack: list) -> None:
        color[n] = GRAY
        for m in edges[n]:
            if color[m] == GRAY:
                raise ComponentError(
                    f"template cycle: {' -> '.join(stack + [n, m])}"
                )
            if color[m] == WHITE:
                visit(m, stack + [n])
        color[n] = BLACK

    for n in by_name:
        if color[n] == WHITE:
            visit(n, [])


def workflow_step_commands(wf: dict) -> dict:
    """template name → container command list, for harness-side execution
    and for tests asserting the step inventory."""
    out = {}
    for t in (wf.get("spec") or {}).get("templates") or []:
        container = t.get("container")
        if container and container.get("command"):
            out[t["name"]] = list(container["command"])
    return out


def run_component(app_dir: str, component: str, overrides: dict | None,
                  tfjob_version: str = "v1alpha2",
                  junit_path: str | None = None,
                  num_trials: int = 1,
                  smoke: bool = True) -> bool:
    """Deploy a rendered TFJob component against a LocalCluster and run the
    full test_runner verification (the reference's `run-tests` Argo step,
    workflows.libsonnet:281-295).

    With ``smoke`` (the default), container commands are replaced by the e2e
    smoke command before submission: the LocalCluster kubelet executes pod
    commands as real local subprocesses, and the manifest's in-cluster
    command (launcher.tpu_smoke) needs a TPU runtime this harness host may
    not have.  ``smoke=False`` submits the manifest verbatim (real-cluster
    runs through a REST clientset).
    """
    from k8s_tpu.e2e.components import smoke_command
    from k8s_tpu.e2e.local import LocalCluster
    from k8s_tpu.harness import test_runner

    docs = render_component(app_dir, component, overrides)
    if len(docs) != 1:
        raise ComponentError(
            f"component {component!r} rendered {len(docs)} documents; "
            "run expects exactly one TFJob"
        )
    job = docs[0]
    if job.get("kind") != "TFJob":
        raise ComponentError(f"component {component!r} is not a TFJob")
    if smoke:
        for spec in (job["spec"].get("tfReplicaSpecs") or {}).values():
            for c in spec["template"]["spec"].get("containers") or []:
                c["command"] = smoke_command()

    namespace = job["metadata"].get("namespace", "default")
    with LocalCluster(version=tfjob_version, namespace=namespace) as cluster:
        case = test_runner.run_test(
            cluster.clientset, job, tfjob_version=tfjob_version,
            num_trials=num_trials, junit_path=junit_path,
        )
    if case.failure:
        log.error("component %s failed: %s", component, case.failure)
        return False
    log.info("component %s passed in %.1fs", component, case.time)
    return True


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="verb", required=True)

    for verb in ("render", "run"):
        p = sub.add_parser(verb)
        p.add_argument("--app_dir", required=True)
        p.add_argument("--component", required=True)
        p.add_argument("--params", default="", help="k=v,k2=v2 overrides")
        if verb == "run":
            p.add_argument("--tfjob_version", default="v1alpha2")
            p.add_argument("--junit_path", default=None)
            p.add_argument("--num_trials", type=int, default=1)
            p.add_argument(
                "--no-smoke", dest="smoke", action="store_false",
                help="Submit the manifest's real command instead of the "
                "local smoke substitution.",
            )

    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    overrides = parse_params(args.params)

    if args.verb == "render":
        docs = render_component(args.app_dir, args.component, overrides)
        for doc in docs:
            if doc.get("kind") == "Workflow":
                validate_workflow(doc)
        yaml.safe_dump_all(docs, sys.stdout, sort_keys=False)
        return 0

    ok = run_component(
        args.app_dir, args.component, overrides,
        tfjob_version=args.tfjob_version, junit_path=args.junit_path,
        num_trials=args.num_trials, smoke=args.smoke,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

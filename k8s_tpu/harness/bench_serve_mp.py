"""Multi-host tensor-parallel serving bench (ISSUE 14): 1-process vs
4-process CPU mesh over the SAME fixed-seed three-lane workload.

    python -m k8s_tpu.harness.bench_serve_mp --processes 4

Each arm is a REAL serving gang (models/mp_serve.run_serve_gang): N OS
processes under the operator env contract, ``jax.distributed`` + gloo
collectives, params tensor-sharded, the KV pool head-sharded per host,
the chief broadcasting the per-step batch plan.  Both arms run a
compile-warming pass first, then the timed script (greedy + sampled +
speculative lanes mixed), so the comparison measures serving, not
tracing.

Embedded assertions (a violation attaches ``failures`` and raises with
the artifact on the exception — the bench_churn.json contract; the
artifact lands on failure too):

- **token identity**: the N-process mesh emits byte-identical tokens to
  the 1-process mesh for every request of every lane — the ROADMAP
  item 3 correctness bar, end to end through real processes;
- **memory sharding**: each process holds ~1/N of the KV pool and the
  tensor-sharded params (the reason multi-host serving exists: models
  that do not fit one chip), asserted from each worker's MEASURED
  addressable-shard sizes (mesh_serve.local_fraction), with the
  spec-derived expectation alongside in the artifact;
- **mesh overhead floor**: N-process aggregate tokens/s >=
  ``efficiency_floor`` x single-host (default 0.12).  NOTE the honest
  scope: this CPU mesh runs its per-layer psums over gloo TCP loopback
  (millisecond-class latency); the TPU target — tokens/s per chip
  within 20% of single-host, i.e. efficiency ~0.8 — needs ICI-class
  microsecond collectives and is recorded in the artifact as
  ``per_chip_tpu_target`` for the hardware run to assert
  (docs/performance.md carries the measured CPU numbers and the
  derivation).  The CI floor exists to catch mechanism regressions (a
  serialization bug, a pool re-gather, a per-step recompile) that tank
  the ratio, not to prove ICI scaling on a laptop;
- **compile budgets per process**: the chief's engine seams AND every
  worker's mirrored seams stay within their declared budgets
  (K8S_TPU_COMPILE_LEDGER=1 is exported to the gang);
- **clean gang exits**: every process exits 0 in both arms.

Emits one JSON line (bench.py contract); ``--out`` additionally writes
the ``bench_serve_mp.json`` artifact, on failure too with a
``failures`` field.
"""

from __future__ import annotations

import argparse
import json
import logging

log = logging.getLogger(__name__)

# calibrated regression floor for the gloo-loopback CPU mesh: measured
# 0.26-0.34 on the 24-core reference box at hidden=256/layers=4,
# slots 8-16 (see docs/performance.md); 0.12 leaves CI-noise headroom
# while still catching anything that serializes the mesh
DEFAULT_EFFICIENCY_FLOOR = 0.12
PER_CHIP_TPU_TARGET = 0.8


def bench_script(requests: int, max_new: int) -> list[dict]:
    """The mixed three-lane fixed-seed workload both arms serve."""
    out: list[dict] = []
    for i in range(requests):
        lane = i % 3
        base = [(i * 13 + j * 7 + 1) % 256 for j in range(8)]
        if lane == 0:
            out.append({"tokens": base, "max_new_tokens": max_new})
        elif lane == 1:
            out.append({"tokens": base, "max_new_tokens": max_new,
                        "temperature": 1.0, "seed": 100 + i})
        else:
            cyc = [(i * 29 + j * 11 + 3) % 256 for j in range(5)]
            out.append({"tokens": [cyc[j % 5] for j in range(20)],
                        "max_new_tokens": max_new, "speculative": 4,
                        "seed": 200 + i})
    return out


def _arm(n: int, script: list, *, slots: int, threads: int, hidden: int,
         layers: int, timeout: float) -> tuple:
    from k8s_tpu.models import mp_serve

    res, workers = mp_serve.run_serve_gang(
        n, script=script, slots=slots, threads=threads, hidden=hidden,
        layers=layers, heads=8, max_seq_len=128, timeout=timeout,
        warmup=True, extra_env={"K8S_TPU_COMPILE_LEDGER": "1"})
    return res, workers


def run_bench(processes: int = 4, requests: int = 24, max_new: int = 24,
              slots: int = 8, threads: int = 10, hidden: int = 256,
              layers: int = 4, timeout: float = 420.0,
              efficiency_floor: float = DEFAULT_EFFICIENCY_FLOOR) -> dict:
    script = bench_script(requests, max_new)
    failures: list[str] = []
    arms: dict[int, dict] = {}
    worker_audits: dict[int, list] = {}
    for n in (1, processes):
        res, workers = _arm(n, script, slots=slots, threads=threads,
                            hidden=hidden, layers=layers, timeout=timeout)
        if not res.success or res.chief_result is None:
            tail = res.worker_outputs[-1][-800:] if res.worker_outputs \
                else ""
            failures.append(
                f"{n}-process gang failed: exit codes {res.exit_codes}: "
                f"{tail}")
            arms[n] = {"exit_codes": res.exit_codes}
            continue
        c = res.chief_result
        arms[n] = {
            "num_processes": c["num_processes"],
            "plan_bus": c.get("plan_bus"),
            "tp_degree": c["tp_degree"],
            "tokens": c["tokens"],
            "wall_s": c["wall_s"],
            "tokens_per_s": c["tokens_per_s"],
            "decode_programs": c["decode_programs"],
            "prefill_programs": c["prefill_programs"],
            "spec_mean_accepted": c["spec_mean_accepted"],
            "compile_ledger": c["compile_ledger"],
            "errors": c["errors"],
            "gang_duration_s": round(res.duration_s, 1),
            "results": c["results"],
        }
        worker_audits[n] = workers
        if c["errors"]:
            failures.append(f"{n}-process arm request errors: "
                            f"{c['errors'][:3]}")

    result: dict = {
        "metric": "serve_mp_tokens_per_s",
        "value": arms.get(processes, {}).get("tokens_per_s"),
        "unit": "tok/s",
        "processes": processes,
        "requests": requests,
        "max_new": max_new,
        "slots": slots,
        "threads": threads,
        "model": {"hidden": hidden, "layers": layers, "heads": 8},
        "per_chip_tpu_target": PER_CHIP_TPU_TARGET,
        "efficiency_floor": efficiency_floor,
        "single_host": {k: v for k, v in arms.get(1, {}).items()
                        if k != "results"},
        "mesh": {k: v for k, v in arms.get(processes, {}).items()
                 if k != "results"},
        "worker_audits": worker_audits.get(processes, []),
    }

    one, many = arms.get(1), arms.get(processes)
    if one and many and "results" in one and "results" in many:
        # -- token identity: the correctness bar ------------------------
        identical = one["results"] == many["results"]
        result["token_identity_ok"] = identical
        if not identical:
            diffs = [i for i, (a, b) in enumerate(
                zip(one["results"], many["results"])) if a != b]
            failures.append(
                f"{processes}-process mesh diverged from 1-process on "
                f"requests {diffs[:8]}: tensor-parallel decode is not "
                "output-invariant")
        # -- mesh overhead floor ---------------------------------------
        eff = many["tokens_per_s"] / max(one["tokens_per_s"], 1e-9)
        result["mp_efficiency"] = round(eff, 3)
        if eff < efficiency_floor:
            failures.append(
                f"{processes}-process mesh at {many['tokens_per_s']} "
                f"tok/s is {round(eff, 3)}x single-host "
                f"{one['tokens_per_s']} tok/s (< {efficiency_floor} "
                "floor): the plan/collective machinery is eating the "
                "mesh (serialized steps? pool re-gather? per-step "
                "recompile?)")
        # -- plan pipelining overlap (ISSUE 15 satellite) --------------
        # the chief's broadcast must be an enqueue, not a socket wait:
        # total enqueue-wait seconds a small fraction of the sender
        # thread's actual send seconds proves the dispatch really
        # overlaps the bus I/O (an un-pipelined bus has enqueue == send
        # by definition, which fails this)
        bus = many.get("plan_bus") or {}
        result["plan_bus"] = bus
        if not bus.get("pipelined"):
            failures.append(
                "plan bus is not pipelined: chunked-prefill broadcasts "
                "serialize behind socket I/O again")
        elif bus.get("send_error"):
            failures.append(
                f"plan bus sender died mid-run: {bus['send_error']}")
        elif bus.get("broadcasts", 0) > 0:
            enq = bus.get("enqueue_wait_s", 0.0)
            snd = bus.get("send_s", 0.0)
            result["plan_overlap_ratio"] = round(
                enq / snd, 4) if snd else None
            if enq > max(0.5 * snd, 0.005 * bus["broadcasts"]):
                failures.append(
                    f"plan enqueue wait {enq}s is not small vs send "
                    f"{snd}s over {bus['broadcasts']} broadcasts: the "
                    "pipeline is not overlapping (queue backpressure or "
                    "a lock on the enqueue path)")
        # -- compile budgets per process -------------------------------
        for label, audit in [("chief-1p", one.get("compile_ledger")),
                             (f"chief-{processes}p",
                              many.get("compile_ledger"))] + [
                (f"worker-{w.get('process_id')}",
                 w.get("compile_ledger"))
                for w in worker_audits.get(processes, [])]:
            if audit is None:
                failures.append(
                    f"{label}: no compile-ledger audit (the gang runs "
                    "under K8S_TPU_COMPILE_LEDGER=1; a missing audit "
                    "means a process never declared its seams)")
            elif audit["over_budget"]:
                failures.append(
                    f"{label}: compile seams over budget "
                    f"{audit['over_budget']}: per-process program "
                    "inventory no longer bounds the compile surface")
        # -- memory sharding: 1/N pool + params per host, MEASURED -----
        # from each worker's addressable shards (mesh_serve.
        # local_fraction) — the spec-derived numbers ride the artifact
        # as the expectation, the assertion reads runtime reality so a
        # silent pool replication fails here
        expect = _shard_fractions(processes, hidden, layers)
        result["shard_fractions_expected"] = expect
        measured = [(w.get("process_id"), w.get("pool_local_fraction"),
                     w.get("params_local_fraction"))
                    for w in worker_audits.get(processes, [])]
        result["shard_fractions_measured"] = [
            {"process_id": p, "pool": pf, "params": prf}
            for p, pf, prf in measured]
        for pid, pool_frac, param_frac in measured:
            if pool_frac is None or \
                    abs(pool_frac - 1.0 / processes) > 0.02:
                failures.append(
                    f"worker {pid} holds {pool_frac} of the KV pool, "
                    f"expected ~1/{processes}: the pool is not "
                    "head-sharded (a replicated pool forfeits the "
                    "memory win multi-host serving exists for)")
            if param_frac is None or \
                    param_frac > expect["params"] + 0.05:
                failures.append(
                    f"worker {pid} holds {param_frac} of the params, "
                    f"expected ~{expect['params']}: tensor sharding is "
                    "not splitting the transformer weights")

    # arms dropped the big results lists from the artifact copy above;
    # keep a compact identity digest instead
    if one and "results" in one:
        result["results_digest"] = _digest(one["results"])

    if failures:
        result["failures"] = failures
        err = RuntimeError("serve-mp bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def _digest(results: list) -> str:
    import hashlib

    return hashlib.sha1(
        json.dumps(results, sort_keys=True).encode()).hexdigest()[:16]


def _shard_fractions(tp: int, hidden: int, layers: int) -> dict:
    """Per-host memory share of the KV pool and params under the serve
    tp specs, computed from the sharding rules themselves (in-process —
    the same spec functions the gang compiles with)."""
    from jax.sharding import PartitionSpec as P

    from k8s_tpu.models.mp_serve import build_model
    from k8s_tpu.parallel.sharding import serve_tp_param_specs

    import jax

    config, params = build_model(0, hidden=hidden, layers=layers, heads=8,
                                 max_seq_len=128)
    specs = serve_tp_param_specs(params)
    total = 0
    local = 0.0
    def sharded(spec: P) -> bool:
        return any(a == "tp" or (isinstance(a, tuple) and "tp" in a)
                   for a in spec)

    for leaf, spec in zip(jax.tree.leaves(params),
                          jax.tree.leaves(specs,
                                          is_leaf=lambda s:
                                          isinstance(s, P))):
        n = leaf.size
        total += n
        local += n / (tp if sharded(spec) else 1)
    # pool leaves shard the kv-head axis over tp by construction
    # (serve_pool_spec), so the per-host share is exactly 1/tp as long
    # as kv_heads % tp == 0 — which MeshPlacement enforces
    return {"params": round(local / max(total, 1), 3),
            "pool": round(1.0 / tp, 3)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--processes", type=int, default=4)
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--max-new", type=int, default=24)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--threads", type=int, default=10)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--timeout", type=float, default=420.0)
    p.add_argument("--efficiency-floor", type=float,
                   default=DEFAULT_EFFICIENCY_FLOOR)
    p.add_argument("--out", default=None,
                   help="also write the JSON artifact to this path")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)

    def _write(payload: dict) -> None:
        line = json.dumps(payload)
        print(line)
        if args.out:
            import os

            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                f.write(line + "\n")

    try:
        result = run_bench(
            processes=args.processes, requests=args.requests,
            max_new=args.max_new, slots=args.slots, threads=args.threads,
            hidden=args.hidden, layers=args.layers, timeout=args.timeout,
            efficiency_floor=args.efficiency_floor)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write(partial)
        raise
    _write(result)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""CI/test/release harness (reference: py/ — SURVEY.md §2.2).

The reference harness runs outside the cluster and talks to GCS + GKE; this
rebuild keeps the same behavioral surface (junit artifacts, prow metadata
files, job-lifecycle client, event-based e2e assertions) against a pluggable
artifact store (local filesystem in the zero-egress image) and the k8s_tpu
clientset (fake or REST backend).
"""

from k8s_tpu.harness.artifacts import LocalArtifactStore, split_uri  # noqa: F401
from k8s_tpu.harness.junit import (  # noqa: F401
    TestCase,
    TestSuite,
    create_junit_xml_file,
    create_xml,
    get_num_failures,
    wrap_test,
)
from k8s_tpu.harness.util import TimeoutError  # noqa: F401

"""JUnit XML emission (reference: py/test_util.py:15-187).

Same behavioral contract as the reference:
- a case with neither a time nor a failure is reported as
  "Test was not run." (test_util.py:131-133);
- suite attributes carry failures / tests / total time;
- ``get_num_failures`` reads the suite's ``failures`` attribute.
"""

from __future__ import annotations

import logging
import os
import subprocess
import time
from typing import Iterable, Optional
from xml.etree import ElementTree

from k8s_tpu.harness.artifacts import is_store_uri, split_uri

log = logging.getLogger(__name__)


class TestCase:
    __test__ = False  # junit artifact class, not a pytest case

    def __init__(self, class_name: str = "", name: str = ""):
        self.class_name = class_name
        self.name = name
        self.time: Optional[float] = None  # seconds
        self.failure: Optional[str] = None


class TestSuite:
    """A named collection of TestCases (test_util.py:26-69)."""

    __test__ = False  # junit artifact class, not a pytest case

    def __init__(self, class_name: str):
        self._cases: dict[str, TestCase] = {}
        self._class_name = class_name

    def create(self, name: str) -> TestCase:
        if name in self._cases:
            raise ValueError(f"TestSuite already has a test named {name}")
        case = TestCase(class_name=self._class_name, name=name)
        self._cases[name] = case
        return case

    def get(self, name: str) -> TestCase:
        if name not in self._cases:
            raise KeyError(f"No TestCase named {name}")
        return self._cases[name]

    def __iter__(self):
        return iter(self._cases.values())

    def __len__(self):
        return len(self._cases)


def _failure_text(exc: BaseException) -> str:
    """Render an exception into the junit <failure> body.

    Subprocess failures carry the captured output (the exit status alone is
    useless in CI artifacts); everything else is summarized by its message.
    The junit *schema* matches the reference's emitter (test_util.py:72-97)
    but the wording and structure here are our own.
    """
    if isinstance(exc, subprocess.CalledProcessError):
        out = exc.output
        if isinstance(out, bytes):  # run_and_output failures carry bytes
            out = out.decode(errors="replace")
        return (
            f"command exited with status {exc.returncode}\n"
            f"captured output:\n{out or ''}"
        )
    return f"{type(exc).__name__}: {exc}"


def wrap_test(test_func, test_case: TestCase) -> None:
    """Run ``test_func``, stamping wall time and any failure into
    ``test_case``.  Exceptions propagate to the caller after being
    recorded — the junit artifact is a side channel, not a handler."""
    start = time.monotonic()
    try:
        test_func()
    except BaseException as e:  # noqa: BLE001 — record *everything*, re-raise
        test_case.failure = _failure_text(e)
        raise
    finally:
        test_case.time = time.monotonic() - start


def create_xml(test_cases: Iterable[TestCase]) -> ElementTree.ElementTree:
    """Build the <testsuite> tree (test_util.py:99-146)."""
    cases = list(test_cases)
    total_time = sum(c.time for c in cases if c.time is not None)
    failures = sum(1 for c in cases if c.failure)
    # Count not-run cases as failures up front so the suite attribute is
    # consistent with the <failure> elements emitted below.  "Not run" means
    # time is None — a measured 0.0s is a (fast) run, not a skip.
    failures += sum(1 for c in cases if c.time is None and not c.failure)
    root = ElementTree.Element(
        "testsuite",
        {
            "failures": str(failures),
            "tests": str(len(cases)),
            "time": str(total_time),
        },
    )
    for c in cases:
        attrib = {"classname": c.class_name, "name": c.name}
        if c.time is not None:
            attrib["time"] = str(c.time)
        if c.time is None and not c.failure:
            c.failure = "Test was not run."
        e = ElementTree.Element("testcase", attrib)
        root.append(e)
        if c.failure:
            f = ElementTree.Element("failure")
            f.text = c.failure
            e.append(f)
    return ElementTree.ElementTree(root)


def create_junit_xml_file(
    test_cases: Iterable[TestCase], output_path: str, store=None
) -> None:
    """Write junit XML to a local path or a store URI
    (test_util.py:149-184)."""
    tree = create_xml(test_cases)
    log.info("Creating %s", output_path)
    if is_store_uri(output_path):
        if store is None:
            raise ValueError(f"store required for URI output {output_path!r}")
        bucket, path = split_uri(output_path)
        store.upload_from_string(
            bucket, path, ElementTree.tostring(tree.getroot(), encoding="unicode")
        )
        return
    dir_name = os.path.dirname(output_path)
    if dir_name:
        os.makedirs(dir_name, exist_ok=True)
    tree.write(output_path)


def get_num_failures(xml_string: str | bytes) -> int:
    """Number of failures recorded in a junit string
    (test_util.py:187-191)."""
    e = ElementTree.fromstring(xml_string)
    return int(e.attrib.get("failures", 0))

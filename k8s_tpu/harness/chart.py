"""Chart rendering: values + templates -> manifest documents.

The reference ships a Helm chart (examples/tf_job/) and deploys its e2e
component through ksonnet parameter substitution (py/test_runner.py:239-276).
Both reduce to the same operation — merge values into templates and apply —
implemented here with ``string.Template`` so the image needs no helm/ks
binary.  Charts live as a directory: Chart.yaml + values.yaml +
templates/*.yaml.
"""

from __future__ import annotations

import os
import string

import yaml


class ChartError(Exception):
    pass


def load_values(chart_dir: str, overrides: dict | None = None) -> dict:
    path = os.path.join(chart_dir, "values.yaml")
    values = {}
    if os.path.exists(path):
        with open(path) as f:
            values = yaml.safe_load(f) or {}
    if overrides:
        values.update(overrides)
    return values


def render_chart(chart_dir: str, overrides: dict | None = None) -> list[dict]:
    """Render every template in the chart; returns parsed YAML documents.
    Raises ChartError on an unresolved ``${var}`` (Helm fails the same way on
    a missing .Values key)."""
    tmpl_dir = os.path.join(chart_dir, "templates")
    if not os.path.isdir(tmpl_dir):
        raise ChartError(f"no templates/ under {chart_dir}")
    values = load_values(chart_dir, overrides)
    docs: list[dict] = []
    for fname in sorted(os.listdir(tmpl_dir)):
        if not fname.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tmpl_dir, fname)) as f:
            text = f.read()
        try:
            rendered = string.Template(text).substitute(
                {k: str(v) for k, v in values.items()}
            )
        except KeyError as e:
            raise ChartError(f"{fname}: no value for ${{{e.args[0]}}}") from None
        for doc in yaml.safe_load_all(rendered):
            if doc:
                docs.append(doc)
    return docs


def chart_metadata(chart_dir: str) -> dict:
    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        return yaml.safe_load(f) or {}

"""Cluster lifecycle for test runs (reference: py/deploy.py:91-277).

The reference's ``setup`` creates a GKE cluster, installs GPU drivers, and
ksonnet-deploys the operator; ``teardown`` deletes the cluster.  Here the
same two verbs target either:

- ``local`` — the in-process fake cluster + operator + kubelet simulator
  (k8s_tpu/e2e/local.py), the default for hermetic runs, or
- ``kubectl`` — a real cluster reachable through kubectl: apply the CRDs and
  an operator Deployment rendered by :func:`operator_manifests`.

Both paths produce the same artifact: a running operator that the test runner
(k8s_tpu/harness/test_runner.py) can submit TFJobs to.
"""

from __future__ import annotations

import argparse
import logging
import os

import yaml

from k8s_tpu.harness import util as harness_util

log = logging.getLogger(__name__)

DEFAULT_NAMESPACE = "kubeflow"


def operator_manifests(
    image: str = "k8s-tpu/tf-job-operator:latest",
    namespace: str = DEFAULT_NAMESPACE,
    version: str = "v1alpha2",
) -> list[dict]:
    """Namespace + ServiceAccount + RBAC + Deployment for the operator (the
    ksonnet component the reference applies, py/deploy.py:49-88).  The
    ClusterRole covers everything the controllers touch: tfjobs (CRD), pods,
    services, events, endpoints (leader election), and PDBs (gang
    scheduling)."""
    labels = {"name": "tf-job-operator"}
    return [
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": namespace}},
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": "tf-job-operator", "namespace": namespace},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "tf-job-operator"},
            "rules": [
                {
                    "apiGroups": ["kubeflow.org"],
                    "resources": ["tfjobs", "tfjobs/status"],
                    "verbs": ["*"],
                },
                {
                    "apiGroups": ["apiextensions.k8s.io"],
                    "resources": ["customresourcedefinitions"],
                    "verbs": ["get", "list", "create"],
                },
                {
                    "apiGroups": [""],
                    "resources": ["pods", "services", "endpoints", "events", "namespaces"],
                    "verbs": ["*"],
                },
                {
                    # node-condition awareness for preemption classification
                    # (controller_v2.pod.pod_on_preempted_node): read-only
                    "apiGroups": [""],
                    "resources": ["nodes"],
                    "verbs": ["get", "list", "watch"],
                },
                {
                    "apiGroups": ["policy"],
                    "resources": ["poddisruptionbudgets"],
                    "verbs": ["*"],
                },
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "tf-job-operator"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "tf-job-operator",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": "tf-job-operator",
                    "namespace": namespace,
                }
            ],
        },
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": "tf-job-operator", "namespace": namespace, "labels": labels},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {
                        "serviceAccountName": "tf-job-operator",
                        "containers": [
                            {
                                "name": "tf-job-operator",
                                "image": image,
                                "command": [
                                    "python",
                                    "-m",
                                    "k8s_tpu.cmd.operator_v2"
                                    if version == "v1alpha2"
                                    else "k8s_tpu.cmd.operator",
                                ],
                                "env": [
                                    {"name": "KUBEFLOW_NAMESPACE", "value": namespace}
                                ],
                            }
                        ],
                    },
                },
            },
        },
    ]


def setup_local(version: str = "v1alpha1", enable_gang_scheduling: bool = False):
    """Bring up the in-process cluster; caller owns stop() (deploy.py:91's
    contract: returns once the operator is ready)."""
    from k8s_tpu.e2e.local import LocalCluster

    cluster = LocalCluster(version=version, enable_gang_scheduling=enable_gang_scheduling)
    cluster.__enter__()
    return cluster


def write_manifests(output_dir: str, image: str, namespace: str, version: str,
                    test_app_dir: str | None = None) -> list[str]:
    """Render CRDs + operator manifests to files kubectl can apply.

    With ``test_app_dir``, the operator objects come from the checked-in
    declarative app (test/test-app/components/core.yaml rendered by
    harness.workflows — the reference's ksonnet-app deploy path,
    py/deploy.py:49-88); otherwise from :func:`operator_manifests`.
    """
    os.makedirs(output_dir, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # Both CRD files define the same object name (tfjobs.kubeflow.org), so
    # apply only the one matching the operator generation being deployed.
    crd = "crd.yaml" if version == "v1alpha1" else "crd-v1alpha2.yaml"
    paths = []
    src = os.path.join(repo, "examples", "crd", crd)
    if os.path.exists(src):
        paths.append(src)
    if test_app_dir:
        from k8s_tpu.harness import workflows

        objects = workflows.render_component(
            test_app_dir, "core",
            {"image": image, "namespace": namespace, "tfjob_version": version},
        )
    else:
        objects = operator_manifests(image, namespace, version)
    operator_path = os.path.join(output_dir, "tf-job-operator.yaml")
    with open(operator_path, "w") as f:
        yaml.safe_dump_all(objects, f)
    paths.append(operator_path)
    return paths


def setup_kubectl(image: str, namespace: str, version: str, output_dir: str,
                  test_app_dir: str | None = None) -> None:
    """kubectl-apply the operator onto a live cluster (deploy.py:91-186)."""
    for path in write_manifests(output_dir, image, namespace, version, test_app_dir):
        harness_util.run(["kubectl", "apply", "-f", path])


def teardown_kubectl(namespace: str) -> None:
    """Delete the operator namespace (deploy.py:189-210's cluster delete,
    scoped to what kubectl owns here)."""
    harness_util.run(["kubectl", "delete", "namespace", namespace, "--ignore-not-found"])


def setup_with_provider(provider, args) -> None:
    """Full setup through the provider seam (reference py/deploy.py setup:
    create cluster -> configure kubectl -> deploy operator -> wait for the
    operator Deployment and accelerator capacity)."""
    import datetime

    from k8s_tpu.harness import providers as providers_lib

    provider.create_cluster()
    provider.configure_kubectl()
    setup_kubectl(args.image, args.namespace, args.version,
                  args.output_dir, args.test_app_dir)
    # --wait_timeout_s 0 skips the readiness wait entirely (apply-only
    # workflows, clusters where the operator image can't pull yet)
    if args.wait_timeout_s > 0:
        providers_lib.wait_for_deployment(
            args.namespace, "tf-job-operator",
            datetime.timedelta(seconds=args.wait_timeout_s),
        )
        if getattr(args, "wait_for_tpu", False):
            provider.wait_for_accelerators(
                datetime.timedelta(seconds=args.wait_timeout_s))


def teardown_with_provider(provider, args) -> None:
    """Teardown through the provider: gke deletes the cluster
    (py/deploy.py:189); kubectl deletes only what it deployed."""
    if provider.name == "gke":
        provider.delete_cluster()
    else:
        teardown_kubectl(args.namespace)


def _provider_from_args(args):
    from k8s_tpu.harness import providers as providers_lib

    return providers_lib.make_provider(
        args.mode,
        project=getattr(args, "project", ""),
        zone=getattr(args, "zone", ""),
        cluster=getattr(args, "cluster", ""),
        machine_type=getattr(args, "machine_type", "n2-standard-8"),
        tpu_type=getattr(args, "tpu_type", ""),
        tpu_topology=getattr(args, "tpu_topology", ""),
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)
    setup_p = sub.add_parser("setup")
    setup_p.add_argument("--image", default="k8s-tpu/tf-job-operator:latest")
    setup_p.add_argument("--namespace", default=DEFAULT_NAMESPACE)
    setup_p.add_argument("--version", default="v1alpha2")
    setup_p.add_argument("--output_dir", default="/tmp/k8s-tpu-deploy")
    setup_p.add_argument(
        "--test_app_dir", default=None,
        help="Deploy the operator from this declarative app dir "
        "(test/test-app) instead of the built-in manifests.",
    )
    setup_p.add_argument(
        "--machine_type", default="n2-standard-8",
        help="gke mode: machine type of the default node pool.")
    setup_p.add_argument(
        "--tpu_type", default="",
        help="gke mode: machine type of a TPU node pool to add "
        "(e.g. ct5lp-hightpu-4t).")
    setup_p.add_argument(
        "--tpu_topology", default="",
        help="gke mode: TPU slice topology for the pool (e.g. 2x4).")
    setup_p.add_argument(
        "--wait_for_tpu", action="store_true",
        help="Block until google.com/tpu node capacity is schedulable.")
    setup_p.add_argument(
        "--wait_timeout_s", type=float, default=600.0,
        help="Deadline for the operator/TPU readiness waits.")
    down_p = sub.add_parser("teardown")
    down_p.add_argument("--namespace", default=DEFAULT_NAMESPACE)
    for p in (setup_p, down_p):
        p.add_argument("--mode", choices=["kubectl", "gke"], default="kubectl")
        p.add_argument("--project", default="",
                       help="gke mode: GCP project.")
        p.add_argument("--zone", default="us-central1-a",
                       help="gke mode: cluster zone.")
        p.add_argument("--cluster", default="",
                       help="gke mode: cluster name.")
        p.add_argument(
            "--junit_path", default=None,
            help="Write a junit TestCase for this step (reference "
            "py/deploy.py setup --junit_path contract).",
        )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    from k8s_tpu.harness import junit as junit_lib

    t = junit_lib.TestCase(class_name="deploy", name=args.command)
    try:
        # provider construction happens inside the junit bracket so a bad
        # flag combination is recorded in the artifact, not just a traceback
        if args.command == "setup":
            junit_lib.wrap_test(
                lambda: setup_with_provider(_provider_from_args(args), args), t)
        else:
            junit_lib.wrap_test(
                lambda: teardown_with_provider(_provider_from_args(args), args), t)
    finally:
        if args.junit_path:
            junit_lib.create_junit_xml_file([t], args.junit_path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

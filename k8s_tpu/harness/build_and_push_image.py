"""Generic image builder (reference: py/build_and_push_image.py:55-176).

Renders a ``Dockerfile.template`` into a build context, computes an image tag
from the tree's git hash (plus ``-dirty-<ts>`` when the checkout is modified,
matching build_and_push_image.py's tagging), and runs ``docker build``.  When
no docker binary is present (this image has none) the build stops after
writing the context — a dry run that still lets tests assert the full
context/tag pipeline.
"""

from __future__ import annotations

import argparse
import datetime
import logging
import os
import shutil

from k8s_tpu.harness import util as harness_util

log = logging.getLogger(__name__)


def get_image_tag(repo_dir: str) -> str:
    """<short-sha>[ -dirty-<timestamp> ] (build_and_push_image.py:28-52)."""
    try:
        sha = harness_util.run_and_output(
            ["git", "rev-parse", "--short=8", "HEAD"], cwd=repo_dir
        ).strip()
    except Exception:  # not a git checkout: fall back to a timestamp tag
        return "notag-" + datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    status = harness_util.run_and_output(
        ["git", "status", "--porcelain"], cwd=repo_dir
    ).strip()
    if status:
        sha += "-dirty-" + datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    return sha


def render_dockerfile(template_path: str, context_dir: str, substitutions: dict | None = None) -> str:
    """Copy the Dockerfile template into the context, applying ``{key}``
    substitutions (the template modification step of build_and_push_image.py:69-86)."""
    with open(template_path) as f:
        text = f.read()
    for key, value in (substitutions or {}).items():
        text = text.replace("{" + key + "}", value)
    out = os.path.join(context_dir, "Dockerfile")
    with open(out, "w") as f:
        f.write(text)
    return out


def docker_available() -> bool:
    return shutil.which("docker") is not None


class DockerfileLintError(ValueError):
    """The rendered Dockerfile would not build."""


_DOCKERFILE_INSTRUCTIONS = frozenset({
    "FROM", "RUN", "CMD", "LABEL", "EXPOSE", "ENV", "ADD", "COPY",
    "ENTRYPOINT", "VOLUME", "USER", "WORKDIR", "ARG", "ONBUILD",
    "STOPSIGNAL", "HEALTHCHECK", "SHELL",
})


def _dockerfile_instructions(text: str):
    """(keyword, args) pairs with comments stripped and ``\\`` continuations
    joined — the subset of Dockerfile syntax docker build itself parses."""
    logical: list[str] = []
    buf = ""
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.endswith("\\"):
            buf += line[:-1] + " "
            continue
        logical.append(buf + line)
        buf = ""
    if buf:
        logical.append(buf)
    for line in logical:
        word, _, rest = line.partition(" ")
        yield word.upper(), rest.strip()


def lint_dockerfile(dockerfile_path: str, context_dir: str) -> None:
    """Dry build-check of a RENDERED Dockerfile (VERDICT r4 #7: no docker
    binary exists in this image, so the template would otherwise rot
    silently).  Validates what `docker build` would reject on sight:
    unsubstituted ``{placeholders}``, unknown instructions, a non-FROM
    first instruction, COPY/ADD sources missing from the context,
    ``COPY --from`` naming an undefined stage, and exec-form
    ENTRYPOINT/CMD that is not valid JSON."""
    import json as json_mod
    import re

    with open(dockerfile_path) as f:
        text = f.read()
    # substitution placeholders are single-brace {word}; a leftover one
    # means render_dockerfile was skipped or the mapping missed a key
    # exclude ${VAR} (docker's own variable expansion) and {{ }} escapes
    leftover = re.search(r"(?<![\{\$])\{([a-zA-Z_][a-zA-Z0-9_]*)\}(?!\})",
                         "\n".join(ln for ln in text.splitlines()
                                   if not ln.strip().startswith("#")))
    if leftover:
        raise DockerfileLintError(
            f"unsubstituted template placeholder {{{leftover.group(1)}}}")

    stages: list[str] = []
    seen_from = False
    for word, rest in _dockerfile_instructions(text):
        if word not in _DOCKERFILE_INSTRUCTIONS:
            raise DockerfileLintError(f"unknown instruction {word!r}")
        if not seen_from and word not in ("FROM", "ARG"):
            raise DockerfileLintError(
                f"first instruction must be FROM (or ARG), got {word}")
        if word == "FROM":
            seen_from = True
            m = re.search(r"\bAS\s+(\S+)", rest, re.IGNORECASE)
            stages.append(m.group(1).lower() if m else str(len(stages)))
            if not rest.split():
                raise DockerfileLintError("FROM needs a base image")
        elif word in ("COPY", "ADD"):
            parts = rest.split()
            flags = [p for p in parts if p.startswith("--")]
            operands = [p for p in parts if not p.startswith("--")]
            if len(operands) < 2:
                raise DockerfileLintError(f"{word} needs src... dest: {rest}")
            from_stage = next(
                (f.split("=", 1)[1] for f in flags if f.startswith("--from=")),
                None)
            if from_stage is not None:
                # stage-relative sources can't be checked without building
                # the earlier stage, but the stage itself must exist
                if from_stage.lower() not in stages[:-1] and \
                        not from_stage.isdigit() and "/" not in from_stage \
                        and ":" not in from_stage:
                    raise DockerfileLintError(
                        f"{word} --from={from_stage} names no earlier stage")
                continue
            for src in operands[:-1]:
                if "*" in src or "?" in src or "[" in src:
                    import glob as glob_mod

                    if not glob_mod.glob(os.path.join(context_dir, src)):
                        raise DockerfileLintError(
                            f"{word} source glob {src!r} matches nothing "
                            f"in context {context_dir}")
                elif not os.path.exists(os.path.join(context_dir, src)):
                    raise DockerfileLintError(
                        f"{word} source {src!r} missing from context "
                        f"{context_dir}")
        elif word in ("ENTRYPOINT", "CMD") and rest.startswith("["):
            try:
                parsed = json_mod.loads(rest)
                ok = isinstance(parsed, list) and all(
                    isinstance(x, str) for x in parsed)
            except ValueError:
                ok = False
            if not ok:
                raise DockerfileLintError(
                    f"{word} exec form is not a JSON string array: {rest}")
    if not seen_from:
        raise DockerfileLintError("Dockerfile has no FROM instruction")


def build_and_push(
    dockerfile_template: str,
    context_dir: str,
    image: str,
    repo_dir: str | None = None,
    substitutions: dict | None = None,
    push: bool = False,
) -> str:
    """Build (and optionally push) ``image:<git tag>``; returns the full
    image ref.  Without docker, the rendered context is left in place and the
    ref returned for manifest generation (dry run)."""
    tag = get_image_tag(repo_dir or os.path.dirname(dockerfile_template))
    ref = f"{image}:{tag}"
    rendered = render_dockerfile(dockerfile_template, context_dir, substitutions)
    # always lint the rendered file: without a docker binary this is the
    # only thing standing between the template and silent rot
    lint_dockerfile(rendered, context_dir)
    if not docker_available():
        log.warning("docker not found; context prepared at %s, skipping build of %s", context_dir, ref)
        return ref
    harness_util.run(["docker", "build", "-t", ref, context_dir])
    if push:
        harness_util.run(["docker", "push", ref])
    return ref


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--template", required=True, help="Dockerfile.template path")
    parser.add_argument("--context", required=True, help="build context directory")
    parser.add_argument("--image", required=True, help="image repo (no tag)")
    parser.add_argument("--push", action="store_true")
    parser.add_argument("--substitute", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="template {key} substitution (repeatable); "
                        "an unsubstituted placeholder fails the lint")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    subs = {}
    for item in args.substitute:
        key, sep, value = item.partition("=")
        if not sep:
            parser.error(f"--substitute needs KEY=VALUE, got {item!r}")
        subs[key] = value
    ref = build_and_push(args.template, args.context, args.image,
                         substitutions=subs, push=args.push)
    print(ref)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Generic image builder (reference: py/build_and_push_image.py:55-176).

Renders a ``Dockerfile.template`` into a build context, computes an image tag
from the tree's git hash (plus ``-dirty-<ts>`` when the checkout is modified,
matching build_and_push_image.py's tagging), and runs ``docker build``.  When
no docker binary is present (this image has none) the build stops after
writing the context — a dry run that still lets tests assert the full
context/tag pipeline.
"""

from __future__ import annotations

import argparse
import datetime
import logging
import os
import shutil

from k8s_tpu.harness import util as harness_util

log = logging.getLogger(__name__)


def get_image_tag(repo_dir: str) -> str:
    """<short-sha>[ -dirty-<timestamp> ] (build_and_push_image.py:28-52)."""
    try:
        sha = harness_util.run_and_output(
            ["git", "rev-parse", "--short=8", "HEAD"], cwd=repo_dir
        ).strip()
    except Exception:  # not a git checkout: fall back to a timestamp tag
        return "notag-" + datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    status = harness_util.run_and_output(
        ["git", "status", "--porcelain"], cwd=repo_dir
    ).strip()
    if status:
        sha += "-dirty-" + datetime.datetime.now().strftime("%Y%m%d%H%M%S")
    return sha


def render_dockerfile(template_path: str, context_dir: str, substitutions: dict | None = None) -> str:
    """Copy the Dockerfile template into the context, applying ``{key}``
    substitutions (the template modification step of build_and_push_image.py:69-86)."""
    with open(template_path) as f:
        text = f.read()
    for key, value in (substitutions or {}).items():
        text = text.replace("{" + key + "}", value)
    out = os.path.join(context_dir, "Dockerfile")
    with open(out, "w") as f:
        f.write(text)
    return out


def docker_available() -> bool:
    return shutil.which("docker") is not None


def build_and_push(
    dockerfile_template: str,
    context_dir: str,
    image: str,
    repo_dir: str | None = None,
    substitutions: dict | None = None,
    push: bool = False,
) -> str:
    """Build (and optionally push) ``image:<git tag>``; returns the full
    image ref.  Without docker, the rendered context is left in place and the
    ref returned for manifest generation (dry run)."""
    tag = get_image_tag(repo_dir or os.path.dirname(dockerfile_template))
    ref = f"{image}:{tag}"
    render_dockerfile(dockerfile_template, context_dir, substitutions)
    if not docker_available():
        log.warning("docker not found; context prepared at %s, skipping build of %s", context_dir, ref)
        return ref
    harness_util.run(["docker", "build", "-t", ref, context_dir])
    if push:
        harness_util.run(["docker", "push", ref])
    return ref


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--template", required=True, help="Dockerfile.template path")
    parser.add_argument("--context", required=True, help="build context directory")
    parser.add_argument("--image", required=True, help="image repo (no tag)")
    parser.add_argument("--push", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    ref = build_and_push(args.template, args.context, args.image, push=args.push)
    print(ref)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Cluster providers: the cloud-lifecycle seam for test deployments.

The reference provisions real GKE clusters for its e2e runs (py/deploy.py:91
creates the cluster through the GKE API and waits on the operation;
py/util.py:348 installs the accelerator driver daemonset and py/util.py:375
polls nodes until accelerators are schedulable; py/deploy.py:189 tears the
cluster down).  This module is the same seam, TPU-first:

- every cloud interaction goes through subprocess ``gcloud``/``kubectl`` so
  the provider is unit-testable against PATH shims with no cloud reachable;
- the accelerator wait looks for ``google.com/tpu`` node capacity (TPU node
  pools advertise it via the TPU device plugin — no driver daemonset to
  install, unlike the reference's GPU alpha flow);
- providers share one protocol, so ``deploy.py`` dispatches on ``--mode``
  and the rest of the harness never knows which one it got.
"""

from __future__ import annotations

import datetime
import json
import logging
import subprocess
import time
from dataclasses import dataclass, field

from k8s_tpu.harness import util as harness_util

log = logging.getLogger(__name__)


class ProviderError(RuntimeError):
    """A cluster-lifecycle step failed (non-retryably)."""


class WaitTimeout(ProviderError):
    """Polling for a readiness condition exceeded its deadline."""


class Provider:
    """Cluster lifecycle protocol.

    ``create_cluster``/``delete_cluster`` bracket the test run;
    ``configure_kubectl`` points kubectl at the cluster;
    ``wait_for_accelerators`` blocks until accelerator capacity is
    schedulable (the reference's driver-daemonset wait, py/util.py:375).
    """

    name = "abstract"

    def create_cluster(self) -> None:
        raise NotImplementedError

    def delete_cluster(self) -> None:
        raise NotImplementedError

    def configure_kubectl(self) -> None:
        raise NotImplementedError

    def wait_for_accelerators(self, timeout: datetime.timedelta) -> None:
        raise NotImplementedError


class LocalProvider(Provider):
    """In-process fake cluster: every lifecycle verb is a no-op; the
    LocalCluster context manager owns actual setup (e2e/local.py)."""

    name = "local"

    def create_cluster(self) -> None:
        log.info("local provider: no cluster to create")

    def delete_cluster(self) -> None:
        log.info("local provider: no cluster to delete")

    def configure_kubectl(self) -> None:
        pass

    def wait_for_accelerators(self, timeout=None) -> None:
        pass


class KubectlProvider(Provider):
    """An existing cluster reachable through the current kubectl context:
    lifecycle verbs are no-ops, readiness waits are real."""

    name = "kubectl"

    def create_cluster(self) -> None:
        log.info("kubectl provider: using the existing cluster")

    def delete_cluster(self) -> None:
        log.info("kubectl provider: leaving the existing cluster in place")

    def configure_kubectl(self) -> None:
        pass  # caller's kubeconfig is already the contract

    def wait_for_accelerators(self, timeout=None) -> None:
        wait_for_tpu_nodes(timeout or datetime.timedelta(minutes=10))


@dataclass
class GkeProvider(Provider):
    """GKE cluster lifecycle over subprocess gcloud (py/deploy.py:91-189
    parity; the REST-discovery client there becomes ``gcloud`` here).

    ``tpu_topology``/``tpu_type`` request a TPU node pool at create time
    (e.g. type ``ct5lp-hightorch-...``/topology ``2x4``); without them the
    cluster is CPU-only, as the reference's is without ``--accelerator``.
    """

    project: str
    zone: str
    cluster: str
    machine_type: str = "n2-standard-8"
    num_nodes: int = 1
    tpu_type: str = ""       # GKE machine type of the TPU node pool
    tpu_topology: str = ""   # e.g. "2x4"
    network: str = ""
    name = "gke"
    # operation polling (reference wait_for_operation: py/util.py:226)
    poll_interval: float = 5.0
    create_timeout: datetime.timedelta = field(
        default_factory=lambda: datetime.timedelta(hours=1))

    def _gcloud(self, *args: str) -> str:
        # always run_and_output: the AlreadyExists/NotFound idempotency
        # checks read the failure text off CalledProcessError.output, which
        # plain run() (no capture) would leave empty
        cmd = ["gcloud", f"--project={self.project}", *args]
        return harness_util.run_and_output(cmd)

    def create_cluster(self) -> None:
        cmd = [
            "container", "clusters", "create", self.cluster,
            f"--zone={self.zone}",
            f"--machine-type={self.machine_type}",
            f"--num-nodes={self.num_nodes}",
            "--scopes=cloud-platform",
            "--async",  # returns an operation; we poll status ourselves
        ]
        if self.network:
            cmd.append(f"--network={self.network}")
        try:
            self._gcloud(*cmd)
        except subprocess.CalledProcessError as e:
            # 409 AlreadyExists parity (py/util.py:196): reuse the cluster.
            if "already exists" in _output_text(e).lower():
                log.info("cluster %s already exists; reusing", self.cluster)
            else:
                raise
        self._wait_cluster_status("RUNNING", self.create_timeout)
        if self.tpu_type:
            self._create_tpu_node_pool()

    def _create_tpu_node_pool(self) -> None:
        cmd = [
            "container", "node-pools", "create", "tpu-pool",
            f"--cluster={self.cluster}",
            f"--zone={self.zone}",
            f"--machine-type={self.tpu_type}",
            f"--num-nodes={self.num_nodes}",
        ]
        if self.tpu_topology:
            cmd.append(f"--tpu-topology={self.tpu_topology}")
        try:
            self._gcloud(*cmd)
        except subprocess.CalledProcessError as e:
            if "already exists" in _output_text(e).lower():
                log.info("tpu-pool already exists; reusing")
            else:
                raise

    def _wait_cluster_status(self, want: str,
                             timeout: datetime.timedelta) -> None:
        """Poll `describe` until the cluster reaches ``want`` (the operation
        wait of py/util.py:226, expressed over cluster status)."""
        deadline = time.monotonic() + timeout.total_seconds()
        while True:
            try:
                out = self._gcloud(
                    "container", "clusters", "describe", self.cluster,
                    f"--zone={self.zone}", "--format=json",
                )
                status = (json.loads(out) or {}).get("status", "")
            except subprocess.CalledProcessError:
                # transient describe failure (not-found race right after an
                # async create, network blip): keep polling to the deadline
                status = ""
            except ValueError:
                status = ""  # transiently garbled describe output: keep polling
            if status == want:
                log.info("cluster %s is %s", self.cluster, want)
                return
            if status in ("ERROR", "DEGRADED"):
                raise ProviderError(
                    f"cluster {self.cluster} entered status {status}")
            if time.monotonic() > deadline:
                raise WaitTimeout(
                    f"timed out waiting for cluster {self.cluster} to reach "
                    f"{want} (last status {status!r})")
            time.sleep(self.poll_interval)

    def delete_cluster(self) -> None:
        try:
            self._gcloud(
                "container", "clusters", "delete", self.cluster,
                f"--zone={self.zone}", "--quiet",
            )
        except subprocess.CalledProcessError as e:
            # parity with delete_cluster's log-and-continue (py/util.py:202):
            # a missing cluster is a successful teardown
            if "not found" in _output_text(e).lower():
                log.info("cluster %s already gone", self.cluster)
            else:
                raise

    def configure_kubectl(self) -> None:
        # py/util.py:272
        self._gcloud(
            "container", "clusters", "get-credentials", self.cluster,
            f"--zone={self.zone}",
        )

    def wait_for_accelerators(self, timeout=None) -> None:
        wait_for_tpu_nodes(timeout or datetime.timedelta(minutes=10))


def _output_text(e: subprocess.CalledProcessError) -> str:
    out = e.output
    if isinstance(out, bytes):
        return out.decode(errors="replace")
    return out or ""


def _kubectl_json(*args: str) -> dict:
    out = harness_util.run_and_output(["kubectl", *args, "-o", "json"])
    return json.loads(out or "{}")


def wait_for_tpu_nodes(timeout: datetime.timedelta,
                       poll_interval: float = 15.0) -> None:
    """Block until at least one node advertises schedulable google.com/tpu
    capacity (the reference's wait_for_gpu_driver_install, py/util.py:375,
    retargeted at the TPU device plugin)."""
    deadline = time.monotonic() + timeout.total_seconds()
    while True:
        try:
            nodes = _kubectl_json("get", "nodes").get("items", [])
        except subprocess.CalledProcessError:
            nodes = []  # apiserver warming up right after get-credentials
        for n in nodes:
            cap = ((n.get("status") or {}).get("capacity") or {})
            try:
                if int(cap.get("google.com/tpu", 0)) > 0:
                    log.info("TPU capacity is schedulable")
                    return
            except (TypeError, ValueError):
                continue
        if time.monotonic() > deadline:
            raise WaitTimeout("timed out waiting for TPU node capacity")
        log.info("waiting for TPU nodes (%d nodes present)", len(nodes))
        time.sleep(poll_interval)


def wait_for_deployment(namespace: str, name: str,
                        timeout: datetime.timedelta,
                        poll_interval: float = 10.0) -> dict:
    """Block until a Deployment has a ready replica (py/util.py:280)."""
    deadline = time.monotonic() + timeout.total_seconds()
    while True:
        try:
            deploy = _kubectl_json(
                "get", "deployment", name, "-n", namespace)
        except subprocess.CalledProcessError:
            deploy = {}
        ready = ((deploy.get("status") or {}).get("readyReplicas") or 0)
        if ready >= 1:
            log.info("deployment %s/%s is ready", namespace, name)
            return deploy
        if time.monotonic() > deadline:
            raise WaitTimeout(
                f"timed out waiting for deployment {namespace}/{name}")
        log.info("waiting for deployment %s/%s", namespace, name)
        time.sleep(poll_interval)


def make_provider(mode: str, **kwargs) -> Provider:
    """Factory keyed by the deploy --mode flag."""
    if mode == "local":
        return LocalProvider()
    if mode == "kubectl":
        return KubectlProvider()
    if mode == "gke":
        required = ("project", "zone", "cluster")
        missing = [k for k in required if not kwargs.get(k)]
        if missing:
            raise ProviderError(
                f"gke provider requires {', '.join('--' + m for m in missing)}")
        allowed = {k: v for k, v in kwargs.items()
                   if k in GkeProvider.__dataclass_fields__}
        return GkeProvider(**allowed)
    raise ProviderError(f"unknown provider mode {mode!r}")

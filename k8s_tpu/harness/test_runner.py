"""E2E test runner (reference: py/test_runner.py:147-366).

Deploys a parameterized TFJob component, waits for completion, verifies
pod/service creation **events** against the expected replica counts (events
are load-bearing API — SURVEY.md §5), then deletes and repeats for
``num_trials`` trials to prove delete+recreate with the same name works.
Emits junit XML.

The ksonnet deployment step (``ks env add``/``param set``/``apply``,
test_runner.py:239-276) becomes a pure component function
(k8s_tpu.e2e.components).
"""

from __future__ import annotations

import datetime
import logging
import re
import time

from k8s_tpu.client import errors
from k8s_tpu.harness import junit, tf_job_client
from k8s_tpu.harness.util import TimeoutError, wait_for

log = logging.getLogger(__name__)

# Same pattern the reference greps events with (test_runner.py:193).
CREATED_RE = re.compile(r"Created.*(pod|Service).*: (.*)", re.IGNORECASE)


def get_events(clientset, namespace: str, uid: str) -> list[dict]:
    """Events whose involvedObject matches ``uid``
    (test_runner.py:147-181)."""
    events = clientset.events(namespace).list()
    return [
        e for e in events
        if (e.get("involvedObject") or {}).get("uid") == uid
    ]


def parse_events(events: list[dict]) -> tuple[set, set]:
    """→ (pods_created, services_created) name sets
    (test_runner.py:184-211)."""
    pods, services = set(), set()
    for e in events:
        m = CREATED_RE.match(e.get("message") or "")
        if not m:
            continue
        kind, name = m.group(1).lower(), m.group(2)
        if kind == "pod":
            pods.add(name)
        elif kind == "service":
            services.add(name)
    return pods, services


def get_labels(name: str, runtime_id: str | None) -> dict:
    """Selector labels for a job's pods (test_runner.py:129-137)."""
    labels = {"tf_job_name": name}
    if runtime_id:
        labels["runtime_id"] = runtime_id
    return labels


def to_selector(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in labels.items())


def wait_for_delete(
    clientset,
    namespace: str,
    name: str,
    version: str = "v1alpha1",
    timeout: datetime.timedelta = datetime.timedelta(minutes=2),
    polling_interval: datetime.timedelta = datetime.timedelta(milliseconds=100),
    status_callback=None,
) -> None:
    """Poll until the TFJob is gone (py/test_runner.py:22-44)."""
    client = clientset.tfjobs_unstructured(
        namespace, version if "/" in version else f"kubeflow.org/{version}"
    )

    def gone():
        try:
            obj = client.get(name)
        except errors.ApiError as e:
            if errors.is_not_found(e):
                return True
            raise
        if status_callback:
            status_callback(obj)
        return False

    wait_for(
        gone, timeout.total_seconds(), polling_interval.total_seconds(),
        f"delete of {namespace}/{name}",
    )


def wait_for_pods_to_be_deleted(
    clientset,
    namespace: str,
    pod_labels: dict,
    timeout: datetime.timedelta = datetime.timedelta(minutes=2),
    polling_interval: datetime.timedelta = datetime.timedelta(milliseconds=100),
) -> None:
    """Poll until no pods match the selector (test_runner.py:118-127)."""
    wait_for(
        lambda: not clientset.pods(namespace).list(label_selector=pod_labels),
        timeout.total_seconds(),
        polling_interval.total_seconds(),
        f"pods {pod_labels} to be deleted",
    )


def _expected_replicas(results: dict, version: str) -> int:
    """Σ replicas over the spec, version-aware (test_runner.py:303-315)."""
    if version.endswith("v1alpha1"):
        return sum(
            r.get("replicas", 0)
            for r in (results.get("spec") or {}).get("replicaSpecs", [])
        )
    return sum(
        (spec or {}).get("replicas", 1)
        for spec in ((results.get("spec") or {}).get("tfReplicaSpecs") or {}).values()
    )


def _succeeded(results: dict, version: str) -> bool:
    """v1alpha1: status.state == Succeeded; v1alpha2: last condition type
    Succeeded (test_runner.py:283-299)."""
    status = results.get("status") or {}
    if version.endswith("v1alpha1"):
        return (status.get("state") or "").lower() == "succeeded"
    conditions = status.get("conditions") or []
    if not conditions:
        return False
    return (conditions[-1].get("type") or "").lower() == "succeeded"


def run_test(
    clientset,
    component: dict,
    tfjob_version: str = "v1alpha1",
    num_trials: int = 2,
    junit_path: str | None = None,
    store=None,
    wait_timeout: datetime.timedelta = datetime.timedelta(minutes=2),
    polling_interval: datetime.timedelta = datetime.timedelta(milliseconds=100),
) -> junit.TestCase:
    """The reference's run_test flow (test_runner.py:214-366) against an
    already-provisioned cluster (LocalCluster or a REST backend)."""
    name = component["metadata"]["name"]
    namespace = component["metadata"].get("namespace", "default")

    t = junit.TestCase(class_name="tfjob_test", name=name)
    start = time.time()
    try:
        for trial in range(num_trials):
            log.info("Trial %s", trial)
            tf_job_client.create_tf_job(clientset, component, tfjob_version)
            results = tf_job_client.wait_for_job(
                clientset, namespace, name, tfjob_version,
                timeout=wait_timeout, polling_interval=polling_interval,
                status_callback=tf_job_client.log_status,
            )

            if not _succeeded(results, tfjob_version):
                t.failure = (
                    f"Trial {trial} Job {name} in namespace {namespace} "
                    f"in status {results.get('status')}"
                )
                log.error(t.failure)
                break

            uid = (results.get("metadata") or {}).get("uid")
            created_pods, created_services = parse_events(
                get_events(clientset, namespace, uid)
            )
            num_expected = _expected_replicas(results, tfjob_version)

            creation_failures = []
            if len(created_pods) < num_expected:
                creation_failures.append(
                    f"Expected {num_expected} pods to be created but only "
                    f"got {len(created_pods)} create events."
                )
            if len(created_services) < num_expected:
                creation_failures.append(
                    f"Expected {num_expected} services to be created but only "
                    f"got {len(created_services)} create events."
                )
            if creation_failures:
                t.failure = (
                    f"Trial {trial} Job {name} in namespace {namespace}: "
                    + ", ".join(creation_failures)
                )
                log.error(t.failure)
                break

            runtime_id = (results.get("spec") or {}).get("RuntimeId")
            if runtime_id:
                # v1 cleans up its pods on completion (training.go:387-417)
                wait_for_pods_to_be_deleted(
                    clientset, namespace, get_labels(name, runtime_id),
                    timeout=wait_timeout, polling_interval=polling_interval,
                )
            tf_job_client.delete_tf_job(clientset, namespace, name, tfjob_version)
            wait_for_delete(
                clientset, namespace, name, tfjob_version,
                timeout=wait_timeout, polling_interval=polling_interval,
            )
    except TimeoutError:
        t.failure = f"Timeout waiting for {name} in namespace {namespace} to finish."
        log.exception(t.failure)
    except Exception as e:  # noqa: BLE001 - any failure marks the test failed
        log.exception("There was a problem running the job; Exception %s", e)
        t.failure = f"Exception occured; type {type(e)} message {e}"
    finally:
        t.time = time.time() - start
        if junit_path:
            junit.create_junit_xml_file([t], junit_path, store)
    return t

"""minijs evaluator: tree-walking interpreter over parser.py's AST.

Value model (JS -> Python):
  undefined -> UNDEFINED singleton        null  -> None
  number    -> float                      string -> str
  boolean   -> bool                       object -> JSObject (dict subclass)
  array     -> JSArray (list subclass)    function -> JSFunction / callable
  plus JSRegExp, JSSet, JSPromise.

Host objects (the DOM shim) plug in via a duck-typed protocol:
``js_get(name)`` / ``js_set(name, value)``; anything exposing it can be
read, written, and have its returned callables invoked from script.

Async model: single-threaded with a synchronous microtask queue.  ``await``
drains the queue until its promise settles — the host's fetch() resolves
promises synchronously, so the SPA's entire async surface runs
deterministically inside one test process.
"""

from __future__ import annotations

import json as _json
import math
import re
from typing import Any, Callable, Optional

from k8s_tpu.harness.minijs.parser import parse


class _Undefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEFINED = _Undefined()


class JSError(Exception):
    """Host-level interpreter error (unsupported construct, engine bug)."""


class JSException(Exception):
    """A JS ``throw``; ``value`` is the thrown JS value."""

    def __init__(self, value):
        self.value = value
        super().__init__(js_to_string(value) if not isinstance(value, JSObject)
                         else str(value.get("message", "Error")))


class JSObject(dict):
    """A plain JS object; insertion-ordered like real JS string keys."""


class JSArray(list):
    pass


class JSSet:
    def __init__(self, items=()):
        self.items: list = []
        for x in items:
            self.add(x)

    def add(self, x):
        if not any(strict_equals(x, y) for y in self.items):
            self.items.append(x)
        return self

    def has(self, x) -> bool:
        return any(strict_equals(x, y) for y in self.items)

    def __iter__(self):
        return iter(self.items)


class JSRegExp:
    def __init__(self, source: str, flags: str):
        self.source = source
        self.flags = flags
        py_flags = re.IGNORECASE if "i" in flags else 0
        self.pattern = re.compile(source, py_flags)
        self.global_ = "g" in flags

    def __repr__(self):
        return f"/{self.source}/{self.flags}"


class JSPromise:
    PENDING, FULFILLED, REJECTED = "pending", "fulfilled", "rejected"

    def __init__(self, interp: "Interpreter"):
        self.interp = interp
        self.state = self.PENDING
        self.value: Any = UNDEFINED
        self._callbacks: list[tuple[Optional[Callable], Optional[Callable],
                                    "JSPromise"]] = []

    # -- settling ----------------------------------------------------------

    def resolve(self, value) -> None:
        if self.state != self.PENDING:
            return
        if isinstance(value, JSPromise):  # chain through
            value._on_settled(self.resolve, self.reject)
            return
        self.state = self.FULFILLED
        self.value = value
        self._flush()

    def reject(self, value) -> None:
        if self.state != self.PENDING:
            return
        self.state = self.REJECTED
        self.value = value
        self._flush()

    def _on_settled(self, on_ok, on_err) -> None:
        def cb():
            (on_ok if self.state == self.FULFILLED else on_err)(self.value)
        if self.state == self.PENDING:
            self._callbacks.append((None, None, None))
            # simplest chaining: register via then-machinery
            self.then_native(lambda v: on_ok(v), lambda e: on_err(e))
        else:
            self.interp.microtasks.append(cb)

    def _flush(self) -> None:
        for on_ok, on_err, out in self._callbacks:
            self._schedule(on_ok, on_err, out)
        self._callbacks = []

    def _schedule(self, on_ok, on_err, out: Optional["JSPromise"]) -> None:
        state, value, interp = self.state, self.value, self.interp

        def task():
            handler = on_ok if state == self.FULFILLED else on_err
            if handler is None:  # pass-through
                if out is not None:
                    (out.resolve if state == self.FULFILLED else out.reject)(value)
                return
            try:
                result = handler(value)
            except JSException as e:
                if out is not None:
                    out.reject(e.value)
                return
            if out is not None:
                out.resolve(result)
        interp.microtasks.append(task)

    def then_native(self, on_ok, on_err) -> "JSPromise":
        out = JSPromise(self.interp)
        if self.state == self.PENDING:
            self._callbacks.append((on_ok, on_err, out))
        else:
            self._schedule(on_ok, on_err, out)
        return out


class Environment:
    __slots__ = ("vars", "parent")

    def __init__(self, parent: Optional["Environment"] = None):
        self.vars: dict[str, Any] = {}
        self.parent = parent

    def lookup(self, name: str):
        env = self
        while env is not None:
            if name in env.vars:
                return env.vars[name]
            env = env.parent
        raise JSException(make_error(f"{name} is not defined",
                                     name="ReferenceError"))

    def has(self, name: str) -> bool:
        env = self
        while env is not None:
            if name in env.vars:
                return True
            env = env.parent
        return False

    def set_existing(self, name: str, value) -> None:
        env = self
        while env is not None:
            if name in env.vars:
                env.vars[name] = value
                return
            env = env.parent
        # implicit global (sloppy mode) — the SPA doesn't rely on it, but
        # attribute handlers assigning globals shouldn't crash the harness
        root = self
        while root.parent is not None:
            root = root.parent
        root.vars[name] = value

    def declare(self, name: str, value) -> None:
        self.vars[name] = value


class JSFunction:
    def __init__(self, node: dict, env: Environment, interp: "Interpreter"):
        self.node = node
        self.env = env
        self.interp = interp
        self.name = node.get("name") or ""

    def __call__(self, *args):  # host-side convenience
        return self.interp.call(self, list(args), UNDEFINED)


class NativeFunction:
    # no __slots__: hosts attach js_get / js_construct hooks ad hoc

    def __init__(self, fn: Callable, name: str = ""):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "")

    def __call__(self, *args):
        return self.fn(*args)


def make_error(message: str, name: str = "Error") -> JSObject:
    e = JSObject()
    e["name"] = name
    e["message"] = message
    e["__is_error__"] = True
    return e


# -- conversions -----------------------------------------------------------

def js_truthy(v) -> bool:
    if v is UNDEFINED or v is None or v is False:
        return False
    if isinstance(v, float):
        return not (v == 0 or math.isnan(v))
    if isinstance(v, str):
        return v != ""
    if v is True:
        return True
    return True


def js_to_string(v) -> str:
    if v is UNDEFINED:
        return "undefined"
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float):
        return format_number(v)
    if isinstance(v, str):
        return v
    if isinstance(v, JSArray):
        return ",".join("" if x is UNDEFINED or x is None else js_to_string(x)
                        for x in v)
    if isinstance(v, JSObject):
        if v.get("__is_error__"):
            return f"{v.get('name', 'Error')}: {v.get('message', '')}"
        return "[object Object]"
    if isinstance(v, (JSFunction, NativeFunction)):
        return f"function {getattr(v, 'name', '')}() {{ [code] }}"
    if isinstance(v, JSRegExp):
        return repr(v)
    return str(v)


def format_number(f: float) -> str:
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "Infinity" if f > 0 else "-Infinity"
    if f == int(f) and abs(f) < 1e21:
        return str(int(f))
    return repr(f)


def js_to_number(v) -> float:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, float):
        return v
    if v is UNDEFINED:
        return float("nan")
    if v is None:
        return 0.0
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0.0
        try:
            return float(int(s, 16)) if s.lower().startswith("0x") else float(s)
        except ValueError:
            return float("nan")
    if isinstance(v, JSArray):
        if not v:
            return 0.0
        if len(v) == 1:
            return js_to_number(v[0])
    return float("nan")


def strict_equals(a, b) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, float) and isinstance(b, float):
        return a == b
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    if a is UNDEFINED or b is UNDEFINED or a is None or b is None:
        return a is b
    return a is b  # objects: identity


def loose_equals(a, b) -> bool:
    if (a is None or a is UNDEFINED) and (b is None or b is UNDEFINED):
        return True
    if isinstance(a, (float, str, bool)) and isinstance(b, (float, str, bool)):
        return js_to_number(a) == js_to_number(b) if not (
            isinstance(a, str) and isinstance(b, str)) else a == b
    return strict_equals(a, b)


# -- control-flow signals ----------------------------------------------------

class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class Interpreter:
    """One global realm.  ``run(src)`` executes a program in the realm's
    global environment; ``drain()`` runs queued microtasks to quiescence."""

    MAX_MICROTASK_ROUNDS = 100_000

    def __init__(self):
        self.globals = Environment()
        self.microtasks: list[Callable] = []
        from k8s_tpu.harness.minijs.builtins import install_globals
        install_globals(self)

    # -- host API ----------------------------------------------------------

    def run(self, src: str, env: Optional[Environment] = None):
        program = parse(src)
        env = env or self.globals
        result = UNDEFINED
        self._hoist(program["body"], env)
        for stmt in program["body"]:
            result = self.exec_stmt(stmt, env)
        self.drain()
        return result

    def drain(self) -> None:
        rounds = 0
        while self.microtasks:
            rounds += 1
            if rounds > self.MAX_MICROTASK_ROUNDS:
                raise JSError("microtask queue did not quiesce")
            task = self.microtasks.pop(0)
            task()

    def define(self, name: str, value) -> None:
        self.globals.declare(name, value)

    def native(self, fn: Callable, name: str = "") -> NativeFunction:
        return NativeFunction(fn, name)

    def call(self, fn, args: list, this=UNDEFINED):
        """Invoke a JS or native function from host or script."""
        if isinstance(fn, NativeFunction):
            return fn.fn(*args)
        if isinstance(fn, JSFunction):
            return self._call_jsfunction(fn, args, this)
        if callable(fn):
            return fn(*args)
        raise JSException(make_error(
            f"{js_to_string(fn)} is not a function", name="TypeError"))

    def _call_jsfunction(self, fn: JSFunction, args: list, this):
        node = fn.node
        env = Environment(fn.env)
        if not node["is_arrow"]:
            env.declare("this", this)
            env.declare("arguments", JSArray(args))
        self._bind_params(node["params"], args, env)
        if node["is_async"]:
            promise = JSPromise(self)
            try:
                self._exec_body(node["body"], env)
                promise.resolve(UNDEFINED)
            except _Return as r:
                promise.resolve(r.value)
            except JSException as e:
                promise.reject(e.value)
            return promise
        try:
            self._exec_body(node["body"], env)
        except _Return as r:
            return r.value
        return UNDEFINED

    def _bind_params(self, params: list[dict], args: list, env: Environment):
        i = 0
        for p in params:
            if p["rest"]:
                self._bind_target(p["target"], JSArray(args[i:]), env)
                return
            value = args[i] if i < len(args) else UNDEFINED
            if value is UNDEFINED and p["default"] is not None:
                value = self.eval(p["default"], env)
            self._bind_target(p["target"], value, env)
            i += 1

    def _bind_target(self, target: dict, value, env: Environment):
        t = target["t"]
        if t == "Ident":
            env.declare(target["name"], value)
        elif t == "ArrayPattern":
            items = list(self._iterate(value))
            for k, el in enumerate(target["elements"]):
                if el is None:
                    continue
                self._bind_target(el, items[k] if k < len(items) else UNDEFINED,
                                  env)
        elif t == "ObjectPattern":
            for key, sub in target["props"]:
                self._bind_target(sub, self.get_member(value, key), env)
        else:
            raise JSError(f"bad binding target {t}")

    def _exec_body(self, block: dict, env: Environment) -> None:
        self._hoist(block["body"], env)
        for stmt in block["body"]:
            self.exec_stmt(stmt, env)

    def _hoist(self, stmts: list[dict], env: Environment) -> None:
        for s in stmts:
            if s["t"] == "FuncDecl":
                env.declare(s["name"], JSFunction(s["fn"], env, self))

    # -- statements --------------------------------------------------------

    def exec_stmt(self, node: dict, env: Environment):
        t = node["t"]
        if t == "ExprStmt":
            return self.eval(node["expr"], env)
        if t == "VarDecl":
            for target, init in node["decls"]:
                value = UNDEFINED if init is None else self.eval(init, env)
                self._bind_target(target, value, env)
            return UNDEFINED
        if t == "FuncDecl":
            env.declare(node["name"], JSFunction(node["fn"], env, self))
            return UNDEFINED
        if t == "If":
            if js_truthy(self.eval(node["test"], env)):
                self.exec_stmt(node["cons"], env)
            elif node["alt"] is not None:
                self.exec_stmt(node["alt"], env)
            return UNDEFINED
        if t == "Block":
            block_env = Environment(env)
            self._hoist(node["body"], block_env)
            for s in node["body"]:
                self.exec_stmt(s, block_env)
            return UNDEFINED
        if t == "Return":
            raise _Return(UNDEFINED if node["arg"] is None
                          else self.eval(node["arg"], env))
        if t == "Throw":
            raise JSException(self.eval(node["arg"], env))
        if t == "Break":
            raise _Break()
        if t == "Continue":
            raise _Continue()
        if t == "While":
            while js_truthy(self.eval(node["test"], env)):
                try:
                    self.exec_stmt(node["body"], Environment(env))
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEFINED
        if t == "DoWhile":
            while True:
                try:
                    self.exec_stmt(node["body"], Environment(env))
                except _Break:
                    break
                except _Continue:
                    pass
                if not js_truthy(self.eval(node["test"], env)):
                    break
            return UNDEFINED
        if t == "For":
            loop_env = Environment(env)
            if node["init"] is not None:
                self.exec_stmt(node["init"], loop_env)
            while node["test"] is None or js_truthy(
                    self.eval(node["test"], loop_env)):
                try:
                    self.exec_stmt(node["body"], Environment(loop_env))
                except _Break:
                    break
                except _Continue:
                    pass
                if node["update"] is not None:
                    self.eval(node["update"], loop_env)
            return UNDEFINED
        if t == "ForOf":
            iterable = self.eval(node["iter"], env)
            for item in self._iterate(iterable):
                it_env = Environment(env)
                if node["kind"] is None:
                    self._assign_target(node["target"], item, env)
                else:
                    self._bind_target(node["target"], item, it_env)
                try:
                    self.exec_stmt(node["body"], it_env)
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEFINED
        if t == "ForIn":
            obj = self.eval(node["iter"], env)
            keys = list(obj.keys()) if isinstance(obj, JSObject) else \
                [format_number(float(i)) for i in range(len(obj))] \
                if isinstance(obj, JSArray) else []
            for key in keys:
                it_env = Environment(env)
                if node["kind"] is None:
                    self._assign_target(node["target"], key, env)
                else:
                    self._bind_target(node["target"], key, it_env)
                try:
                    self.exec_stmt(node["body"], it_env)
                except _Break:
                    break
                except _Continue:
                    continue
            return UNDEFINED
        if t == "Try":
            try:
                self.exec_stmt(node["block"], env)
            except JSException as e:
                if node["handler"] is not None:
                    catch_env = Environment(env)
                    if node["param"] is not None:
                        self._bind_target(node["param"], e.value, catch_env)
                    self.exec_stmt(node["handler"], catch_env)
                elif node["finalizer"] is None:
                    raise
                else:
                    self.exec_stmt(node["finalizer"], env)
                    raise
            finally:
                if node["finalizer"] is not None:
                    self.exec_stmt(node["finalizer"], env)
            return UNDEFINED
        if t == "Empty":
            return UNDEFINED
        raise JSError(f"unsupported statement {t}")

    # -- expressions -------------------------------------------------------

    def eval(self, node: dict, env: Environment):
        t = node["t"]
        if t == "Num":
            return node["value"]
        if t == "Str":
            return node["value"]
        if t == "Bool":
            return node["value"]
        if t == "Null":
            return None
        if t == "Ident":
            name = node["name"]
            if name == "undefined":
                return UNDEFINED
            if name == "NaN":
                return float("nan")
            if name == "Infinity":
                return float("inf")
            return env.lookup(name)
        if t == "This":
            return env.lookup("this") if env.has("this") else UNDEFINED
        if t == "Template":
            out = []
            for kind, part in node["quasis"]:
                out.append(part if kind == "str"
                           else js_to_string(self.eval(part, env)))
            return "".join(out)
        if t == "Regex":
            return JSRegExp(node["source"], node["flags"])
        if t == "Array":
            arr = JSArray()
            for el in node["elements"]:
                if el["t"] == "Spread":
                    arr.extend(self._iterate(self.eval(el["arg"], env)))
                else:
                    arr.append(self.eval(el, env))
            return arr
        if t == "Object":
            obj = JSObject()
            for key, value_node in node["props"]:
                if key == "spread" and isinstance(value_node, dict) \
                        and value_node.get("t") not in (None,):
                    src = self.eval(value_node, env)
                    if isinstance(src, JSObject):
                        obj.update(src)
                    continue
                obj[key] = self.eval(value_node, env)
            return obj
        if t == "Func":
            if node.get("name"):
                # named function expression: the name is in scope inside
                # its own body (for recursion) but not outside
                fenv = Environment(env)
                fn = JSFunction(node, fenv, self)
                fenv.declare(node["name"], fn)
                return fn
            return JSFunction(node, env, self)
        if t == "Member":
            return self.get_member(self.eval(node["obj"], env), node["prop"])
        if t == "Index":
            obj = self.eval(node["obj"], env)
            key = self.eval(node["expr"], env)
            return self.get_index(obj, key)
        if t == "Call":
            return self._eval_call(node, env)
        if t == "New":
            callee = self.eval(node["callee"], env)
            args = self._eval_args(node["args"], env)
            ctor = getattr(callee, "js_construct", None)
            if ctor is not None:
                return ctor(args)
            if isinstance(callee, (NativeFunction, JSFunction)):
                return self.call(callee, args, UNDEFINED)
            raise JSException(make_error("not a constructor", name="TypeError"))
        if t == "Assign":
            return self._eval_assign(node, env)
        if t == "Cond":
            return self.eval(node["cons"] if js_truthy(
                self.eval(node["test"], env)) else node["alt"], env)
        if t == "Logical":
            left = self.eval(node["left"], env)
            op = node["op"]
            if op == "&&":
                return self.eval(node["right"], env) if js_truthy(left) else left
            if op == "||":
                return left if js_truthy(left) else self.eval(node["right"], env)
            # ??
            return self.eval(node["right"], env) \
                if left is None or left is UNDEFINED else left
        if t == "Binary":
            return self._eval_binary(node, env)
        if t == "Unary":
            return self._eval_unary(node, env)
        if t == "Update":
            old = js_to_number(self._eval_ref_get(node["target"], env))
            new = old + (1.0 if node["op"] == "++" else -1.0)
            self._assign_target(node["target"], new, env)
            return new if node["prefix"] else old
        if t == "Await":
            return self._eval_await(node, env)
        if t == "Sequence":
            self.eval(node["left"], env)
            return self.eval(node["right"], env)
        if t == "Spread":
            raise JSError("spread outside call/array/object")
        raise JSError(f"unsupported expression {t}")

    def _eval_ref_get(self, target: dict, env: Environment):
        if target["t"] == "Ident":
            return env.lookup(target["name"])
        if target["t"] == "Member":
            return self.get_member(self.eval(target["obj"], env), target["prop"])
        if target["t"] == "Index":
            return self.get_index(self.eval(target["obj"], env),
                                  self.eval(target["expr"], env))
        raise JSError("bad reference")

    def _eval_call(self, node: dict, env: Environment):
        callee = node["callee"]
        args = self._eval_args(node["args"], env)
        if callee["t"] == "Member":
            obj = self.eval(callee["obj"], env)
            fn = self.get_member(obj, callee["prop"])
            return self.call(fn, args, this=obj)
        if callee["t"] == "Index":
            obj = self.eval(callee["obj"], env)
            fn = self.get_index(obj, self.eval(callee["expr"], env))
            return self.call(fn, args, this=obj)
        fn = self.eval(callee, env)
        return self.call(fn, args, UNDEFINED)

    def _eval_args(self, arg_nodes: list[dict], env: Environment) -> list:
        args = []
        for a in arg_nodes:
            if a["t"] == "Spread":
                args.extend(self._iterate(self.eval(a["arg"], env)))
            else:
                args.append(self.eval(a, env))
        return args

    def _eval_assign(self, node: dict, env: Environment):
        op = node["op"]
        if op == "=":
            value = self.eval(node["value"], env)
        else:
            current = self._eval_ref_get(node["target"], env)
            rhs = self.eval(node["value"], env)
            binop = op[:-1]
            value = self._binary_op(binop, current, rhs)
        self._assign_target(node["target"], value, env)
        return value

    def _assign_target(self, target: dict, value, env: Environment) -> None:
        t = target["t"]
        if t == "Ident":
            env.set_existing(target["name"], value)
        elif t == "Member":
            self.set_member(self.eval(target["obj"], env), target["prop"], value)
        elif t == "Index":
            obj = self.eval(target["obj"], env)
            key = self.eval(target["expr"], env)
            self.set_index(obj, key, value)
        elif t == "ArrayPattern":
            items = list(self._iterate(value))
            for k, el in enumerate(target["elements"]):
                if el is not None:
                    self._assign_target(
                        el, items[k] if k < len(items) else UNDEFINED, env)
        else:
            raise JSError(f"bad assignment target {t}")

    def _eval_binary(self, node: dict, env: Environment):
        op = node["op"]
        left = self.eval(node["left"], env)
        right = self.eval(node["right"], env)
        return self._binary_op(op, left, right)

    def _binary_op(self, op: str, left, right):
        if op == "+":
            if isinstance(left, str) or isinstance(right, str) or \
                    isinstance(left, (JSArray, JSObject)) or \
                    isinstance(right, (JSArray, JSObject)):
                return js_to_string(left) + js_to_string(right)
            return js_to_number(left) + js_to_number(right)
        if op == "-":
            return js_to_number(left) - js_to_number(right)
        if op == "*":
            return js_to_number(left) * js_to_number(right)
        if op == "/":
            rn = js_to_number(right)
            ln = js_to_number(left)
            if rn == 0:
                if math.isnan(rn) or math.isnan(ln) or ln == 0:
                    return float("nan")
                return math.copysign(float("inf"), ln) * math.copysign(1, rn)
            return ln / rn
        if op == "%":
            rn = js_to_number(right)
            ln = js_to_number(left)
            if rn == 0 or math.isnan(rn) or math.isnan(ln) or math.isinf(ln):
                return float("nan")
            return math.fmod(ln, rn)
        if op == "===":
            return strict_equals(left, right)
        if op == "!==":
            return not strict_equals(left, right)
        if op == "==":
            return loose_equals(left, right)
        if op == "!=":
            return not loose_equals(left, right)
        if op in ("<", ">", "<=", ">="):
            if isinstance(left, str) and isinstance(right, str):
                a, b = left, right
            else:
                a, b = js_to_number(left), js_to_number(right)
                if math.isnan(a) or math.isnan(b):
                    return False
            return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[op]
        if op == "in":
            if isinstance(right, JSObject):
                return js_to_string(left) in right
            if isinstance(right, JSArray):
                idx = js_to_number(left)
                return 0 <= idx < len(right)
            raise JSException(make_error("'in' on non-object", name="TypeError"))
        if op == "instanceof":
            return False  # no user prototypes in this subset
        raise JSError(f"unsupported binary op {op}")

    def _eval_unary(self, node: dict, env: Environment):
        op = node["op"]
        if op == "typeof":
            arg = node["arg"]
            if arg["t"] == "Ident" and not env.has(arg["name"]) \
                    and arg["name"] not in ("undefined", "NaN", "Infinity"):
                return "undefined"
            return js_typeof(self.eval(arg, env))
        if op == "delete":
            arg = node["arg"]
            if arg["t"] == "Member":
                obj = self.eval(arg["obj"], env)
                if isinstance(obj, JSObject):
                    obj.pop(arg["prop"], None)
                return True
            if arg["t"] == "Index":
                obj = self.eval(arg["obj"], env)
                key = self.eval(arg["expr"], env)
                if isinstance(obj, JSObject):
                    obj.pop(js_to_string(key), None)
                return True
            return True
        value = self.eval(node["arg"], env)
        if op == "!":
            return not js_truthy(value)
        if op == "-":
            return -js_to_number(value)
        if op == "+":
            return js_to_number(value)
        if op == "~":
            return float(~int(js_to_number(value)))
        if op == "void":
            return UNDEFINED
        raise JSError(f"unsupported unary op {op}")

    def _eval_await(self, node: dict, env: Environment):
        value = self.eval(node["arg"], env)
        if not isinstance(value, JSPromise):
            return value
        # synchronous model: drain microtasks until the promise settles
        rounds = 0
        while value.state == JSPromise.PENDING and self.microtasks:
            rounds += 1
            if rounds > self.MAX_MICROTASK_ROUNDS:
                raise JSError("await: microtask storm without settlement")
            self.microtasks.pop(0)()
        if value.state == JSPromise.PENDING:
            raise JSError(
                "await on a promise that never settles (host stubs must "
                "resolve synchronously)")
        if value.state == JSPromise.REJECTED:
            raise JSException(value.value)
        return value.value

    # -- member access -----------------------------------------------------

    def get_member(self, obj, prop: str):
        from k8s_tpu.harness.minijs import builtins as b

        if obj is UNDEFINED or obj is None:
            raise JSException(make_error(
                f"Cannot read properties of {js_to_string(obj)} "
                f"(reading '{prop}')", name="TypeError"))
        getter = getattr(obj, "js_get", None)
        if getter is not None:
            return getter(prop)
        if isinstance(obj, JSObject):
            if prop in obj:
                return obj[prop]
            method = b.object_method(self, obj, prop)
            return method if method is not None else UNDEFINED
        if isinstance(obj, JSArray):
            if prop == "length":
                return float(len(obj))
            method = b.array_method(self, obj, prop)
            if method is None:
                return UNDEFINED
            return method
        if isinstance(obj, str):
            if prop == "length":
                return float(len(obj))
            method = b.string_method(self, obj, prop)
            if method is None:
                return UNDEFINED
            return method
        if isinstance(obj, JSPromise):
            return b.promise_method(self, obj, prop)
        if isinstance(obj, JSSet):
            return b.set_method(self, obj, prop)
        if isinstance(obj, JSRegExp):
            return b.regexp_method(self, obj, prop)
        if isinstance(obj, float):
            return b.number_method(self, obj, prop)
        if isinstance(obj, (JSFunction, NativeFunction)):
            if prop == "name":
                return getattr(obj, "name", "")
            if prop == "call":
                return NativeFunction(
                    lambda this=UNDEFINED, *args:
                        self.call(obj, list(args), this), "call")
            if prop == "apply":
                return NativeFunction(
                    lambda this=UNDEFINED, args=None:
                        self.call(obj, list(args or []), this), "apply")
            return UNDEFINED
        if isinstance(obj, bool):
            return UNDEFINED
        raise JSError(f"cannot read {prop!r} of {type(obj).__name__}")

    def get_index(self, obj, key):
        if isinstance(obj, JSArray):
            if isinstance(key, float) or isinstance(key, bool):
                idx = int(js_to_number(key))
                if 0 <= idx < len(obj):
                    return obj[idx]
                return UNDEFINED
            return self.get_member(obj, js_to_string(key))
        if isinstance(obj, str):
            if isinstance(key, float):
                idx = int(key)
                if 0 <= idx < len(obj):
                    return obj[idx]
                return UNDEFINED
            return self.get_member(obj, js_to_string(key))
        if isinstance(obj, JSObject):
            return obj.get(js_to_string(key), UNDEFINED)
        return self.get_member(obj, js_to_string(key))

    def set_member(self, obj, prop: str, value) -> None:
        if obj is UNDEFINED or obj is None:
            raise JSException(make_error(
                f"Cannot set properties of {js_to_string(obj)} "
                f"(setting '{prop}')", name="TypeError"))
        setter = getattr(obj, "js_set", None)
        if setter is not None:
            setter(prop, value)
            return
        if isinstance(obj, JSObject):
            obj[prop] = value
            return
        if isinstance(obj, JSArray) and prop == "length":
            new_len = int(js_to_number(value))
            del obj[new_len:]
            while len(obj) < new_len:
                obj.append(UNDEFINED)
            return
        raise JSError(f"cannot set {prop!r} on {type(obj).__name__}")

    def set_index(self, obj, key, value) -> None:
        if isinstance(obj, JSArray) and isinstance(key, (float, bool)):
            idx = int(js_to_number(key))
            while len(obj) <= idx:
                obj.append(UNDEFINED)
            obj[idx] = value
            return
        self.set_member(obj, js_to_string(key), value)

    # -- iteration ---------------------------------------------------------

    def _iterate(self, value):
        if isinstance(value, JSArray):
            return list(value)
        if isinstance(value, str):
            return list(value)
        if isinstance(value, JSSet):
            return list(value.items)
        if isinstance(value, JSObject):
            raise JSException(make_error(
                "object is not iterable (arrays, strings, Sets are)",
                name="TypeError"))
        hook = getattr(value, "js_iter", None)
        if hook is not None:
            return list(hook())
        raise JSException(make_error(
            f"{js_to_string(value)} is not iterable", name="TypeError"))


def js_typeof(v) -> str:
    if v is UNDEFINED:
        return "undefined"
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, float):
        return "number"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (JSFunction, NativeFunction)):
        return "function"
    return "object"


# -- JSON bridge (used by builtins and the DOM/fetch shims) ------------------

def py_to_js(v):
    """Recursively convert plain Python JSON-ish data into JS values."""
    if v is None or isinstance(v, str):
        return v
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, dict):
        out = JSObject()
        for k, val in v.items():
            out[str(k)] = py_to_js(val)
        return out
    if isinstance(v, (list, tuple)):
        return JSArray(py_to_js(x) for x in v)
    return v


def js_to_py(v):
    if v is UNDEFINED:
        return None
    if isinstance(v, float):
        return int(v) if v == int(v) and abs(v) < 2**53 else v
    if isinstance(v, JSObject):
        return {k: js_to_py(x) for k, x in v.items()
                if x is not UNDEFINED and not isinstance(
                    x, (JSFunction, NativeFunction))}
    if isinstance(v, JSArray):
        return [js_to_py(x) for x in v]
    return v


def json_stringify(value, space: int = 0) -> str:
    def default_filter(v):
        return not isinstance(v, (JSFunction, NativeFunction)) \
            and v is not UNDEFINED

    def conv(v):
        if v is UNDEFINED:
            return None
        if isinstance(v, float):
            if math.isnan(v) or math.isinf(v):
                return None
            return int(v) if v == int(v) and abs(v) < 2**53 else v
        if isinstance(v, JSObject):
            return {k: conv(x) for k, x in v.items() if default_filter(x)}
        if isinstance(v, JSArray):
            return [conv(x) if default_filter(x) else None for x in v]
        return v

    if value is UNDEFINED or isinstance(value, (JSFunction, NativeFunction)):
        return "undefined"
    indent = int(space) if space else None
    return _json.dumps(conv(value), indent=indent,
                       separators=(",", ": ") if indent else (",", ":"),
                       ensure_ascii=False)


def json_parse(text: str):
    try:
        return py_to_js(_json.loads(text))
    except (ValueError, TypeError) as e:
        raise JSException(make_error(str(e), name="SyntaxError")) from None

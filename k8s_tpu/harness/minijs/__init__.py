"""minijs: a minimal JavaScript interpreter for executing the dashboard SPA
in tests (the App.test.js analogue — reference:
dashboard/frontend/src/components/App.test.js runs the reference SPA under
jest; this image has no node, so the frontend CI tier bundles its own
interpreter).

Scope: the ES2017/ES2020 subset the SPA uses — let/const, functions, arrow
functions (incl. param defaults and array-destructuring params), template
literals (nested), object/array literals with spread, for-of with
destructuring, try/catch/throw, regex literals, async/await over a
synchronous microtask queue, Promise/then/catch, Set, JSON, and the usual
String/Array/Object builtins.  Not a general-purpose engine: no classes, no
generators, no labels, no `with`, no getters/setters, no prototype mutation.
"""

from k8s_tpu.harness.minijs.interp import (  # noqa: F401
    Interpreter,
    JSError,
    JSException,
    UNDEFINED,
)
from k8s_tpu.harness.minijs.lexer import LexError  # noqa: F401
from k8s_tpu.harness.minijs.parser import ParseError, parse  # noqa: F401

"""minijs parser: recursive descent over the lexer's token list, producing
dict-shaped AST nodes ({"t": <type>, ...}).  Backtracking (token index
save/restore) is used only for the arrow-function parameter ambiguity."""

from __future__ import annotations

from typing import Optional

from k8s_tpu.harness.minijs.lexer import Token, tokenize


class ParseError(SyntaxError):
    pass


def n(t: str, **kw) -> dict:
    kw["t"] = t
    return kw


ASSIGN_OPS = {"=", "+=", "-=", "*=", "/=", "%="}
EQUALITY = {"===", "!==", "==", "!="}
RELATIONAL = {"<", ">", "<=", ">="}
ADDITIVE = {"+", "-"}
MULTIPLICATIVE = {"*", "/", "%"}
UNARY = {"!", "-", "+", "~"}


class Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    # -- token plumbing ----------------------------------------------------

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.type != "EOF":
            self.i += 1
        return t

    def at_punct(self, *vals: str) -> bool:
        t = self.peek()
        return t.type == "PUNCT" and t.value in vals

    def at_kw(self, *vals: str) -> bool:
        t = self.peek()
        return t.type == "KEYWORD" and t.value in vals

    def eat_punct(self, val: str) -> None:
        t = self.next()
        if t.type != "PUNCT" or t.value != val:
            raise ParseError(
                f"line {t.line}: expected {val!r}, got {t.type} {t.value!r}")

    def eat_kw(self, val: str) -> None:
        t = self.next()
        if t.type != "KEYWORD" or t.value != val:
            raise ParseError(
                f"line {t.line}: expected keyword {val!r}, got {t.value!r}")

    def error(self, msg: str) -> ParseError:
        return ParseError(f"line {self.peek().line}: {msg}")

    # -- program / statements ---------------------------------------------

    def parse_program(self) -> dict:
        body = []
        while self.peek().type != "EOF":
            body.append(self.parse_statement())
        return n("Program", body=body)

    def parse_statement(self) -> dict:
        t = self.peek()
        if t.type == "PUNCT":
            if t.value == "{":
                return self.parse_block()
            if t.value == ";":
                self.next()
                return n("Empty")
        if t.type == "KEYWORD":
            kw = t.value
            if kw in ("var", "let", "const"):
                s = self.parse_var_decl()
                self.semi()
                return s
            if kw == "function":
                return self.parse_function(is_async=False, as_decl=True)
            if kw == "async" and self.peek(1).type == "KEYWORD" \
                    and self.peek(1).value == "function":
                self.next()
                return self.parse_function(is_async=True, as_decl=True)
            if kw == "if":
                return self.parse_if()
            if kw == "for":
                return self.parse_for()
            if kw == "while":
                return self.parse_while()
            if kw == "do":
                return self.parse_do_while()
            if kw == "return":
                self.next()
                arg = None
                if not (self.at_punct(";", "}") or self.peek().type == "EOF"):
                    arg = self.parse_expression()
                self.semi()
                return n("Return", arg=arg)
            if kw == "throw":
                self.next()
                arg = self.parse_expression()
                self.semi()
                return n("Throw", arg=arg)
            if kw == "break":
                self.next()
                self.semi()
                return n("Break")
            if kw == "continue":
                self.next()
                self.semi()
                return n("Continue")
            if kw == "try":
                return self.parse_try()
        expr = self.parse_expression()
        self.semi()
        return n("ExprStmt", expr=expr)

    def semi(self) -> None:
        """Consume a `;` if present (ASI: tolerate its absence)."""
        if self.at_punct(";"):
            self.next()

    def parse_block(self) -> dict:
        self.eat_punct("{")
        body = []
        while not self.at_punct("}"):
            if self.peek().type == "EOF":
                raise self.error("unterminated block")
            body.append(self.parse_statement())
        self.next()
        return n("Block", body=body)

    def parse_var_decl(self) -> dict:
        kind = self.next().value
        decls = []
        while True:
            target = self.parse_binding_target()
            init = None
            if self.at_punct("="):
                self.next()
                init = self.parse_assignment()
            decls.append((target, init))
            if self.at_punct(","):
                self.next()
                continue
            break
        return n("VarDecl", kind=kind, decls=decls)

    def parse_binding_target(self) -> dict:
        t = self.peek()
        if t.type == "IDENT":
            self.next()
            return n("Ident", name=t.value)
        if self.at_punct("["):
            self.next()
            elements: list[Optional[dict]] = []
            while not self.at_punct("]"):
                if self.at_punct(","):
                    self.next()
                    elements.append(None)  # elision
                    continue
                elements.append(self.parse_binding_target())
                if self.at_punct(","):
                    self.next()
            self.next()
            return n("ArrayPattern", elements=elements)
        if self.at_punct("{"):
            self.next()
            props = []
            while not self.at_punct("}"):
                key = self.next()
                if key.type not in ("IDENT", "STR"):
                    raise self.error("bad object-pattern key")
                if self.at_punct(":"):
                    self.next()
                    props.append((key.value, self.parse_binding_target()))
                else:
                    props.append((key.value, n("Ident", name=key.value)))
                if self.at_punct(","):
                    self.next()
            self.next()
            return n("ObjectPattern", props=props)
        raise self.error(f"bad binding target {t.value!r}")

    def parse_if(self) -> dict:
        self.eat_kw("if")
        self.eat_punct("(")
        test = self.parse_expression()
        self.eat_punct(")")
        cons = self.parse_statement()
        alt = None
        if self.at_kw("else"):
            self.next()
            alt = self.parse_statement()
        return n("If", test=test, cons=cons, alt=alt)

    def parse_for(self) -> dict:
        self.eat_kw("for")
        self.eat_punct("(")
        # for-of / for-in with a declaration
        if self.at_kw("var", "let", "const"):
            kind = self.next().value
            target = self.parse_binding_target()
            if self.at_kw("of", "in"):
                which = self.next().value
                it = self.parse_expression()
                self.eat_punct(")")
                body = self.parse_statement()
                return n("ForOf" if which == "of" else "ForIn",
                         kind=kind, target=target, iter=it, body=body)
            # classic for with declaration init
            init = None
            if self.at_punct("="):
                self.next()
                init = self.parse_assignment()
            decls = [(target, init)]
            while self.at_punct(","):
                self.next()
                t2 = self.parse_binding_target()
                i2 = None
                if self.at_punct("="):
                    self.next()
                    i2 = self.parse_assignment()
                decls.append((t2, i2))
            init_node = n("VarDecl", kind=kind, decls=decls)
            return self._finish_classic_for(init_node)
        if self.at_punct(";"):
            return self._finish_classic_for(None)
        first = self.parse_expression()
        if self.at_kw("of", "in"):
            which = self.next().value
            it = self.parse_expression()
            self.eat_punct(")")
            body = self.parse_statement()
            return n("ForOf" if which == "of" else "ForIn",
                     kind=None, target=first, iter=it, body=body)
        return self._finish_classic_for(n("ExprStmt", expr=first))

    def _finish_classic_for(self, init) -> dict:
        self.eat_punct(";")
        test = None if self.at_punct(";") else self.parse_expression()
        self.eat_punct(";")
        update = None if self.at_punct(")") else self.parse_expression()
        self.eat_punct(")")
        body = self.parse_statement()
        return n("For", init=init, test=test, update=update, body=body)

    def parse_while(self) -> dict:
        self.eat_kw("while")
        self.eat_punct("(")
        test = self.parse_expression()
        self.eat_punct(")")
        return n("While", test=test, body=self.parse_statement())

    def parse_do_while(self) -> dict:
        self.eat_kw("do")
        body = self.parse_statement()
        self.eat_kw("while")
        self.eat_punct("(")
        test = self.parse_expression()
        self.eat_punct(")")
        self.semi()
        return n("DoWhile", test=test, body=body)

    def parse_try(self) -> dict:
        self.eat_kw("try")
        block = self.parse_block()
        param = None
        handler = None
        finalizer = None
        if self.at_kw("catch"):
            self.next()
            if self.at_punct("("):
                self.next()
                param = self.parse_binding_target()
                self.eat_punct(")")
            handler = self.parse_block()
        if self.at_kw("finally"):
            self.next()
            finalizer = self.parse_block()
        if handler is None and finalizer is None:
            raise self.error("try without catch or finally")
        return n("Try", block=block, param=param, handler=handler,
                 finalizer=finalizer)

    def parse_function(self, is_async: bool, as_decl: bool) -> dict:
        self.eat_kw("function")
        name = None
        if self.peek().type == "IDENT":
            name = self.next().value
        elif as_decl:
            raise self.error("function declaration needs a name")
        params = self.parse_params_paren()
        body = self.parse_block()
        fn = n("Func", name=name, params=params, body=body,
               is_async=is_async, is_arrow=False)
        return n("FuncDecl", name=name, fn=fn) if as_decl else fn

    def parse_params_paren(self) -> list[dict]:
        self.eat_punct("(")
        params = []
        while not self.at_punct(")"):
            rest = False
            if self.at_punct("..."):
                self.next()
                rest = True
            target = self.parse_binding_target()
            default = None
            if self.at_punct("="):
                self.next()
                default = self.parse_assignment()
            params.append(n("Param", target=target, default=default, rest=rest))
            if self.at_punct(","):
                self.next()
        self.next()
        return params

    # -- expressions -------------------------------------------------------

    def parse_expression(self) -> dict:
        expr = self.parse_assignment()
        while self.at_punct(","):
            self.next()
            right = self.parse_assignment()
            expr = n("Sequence", left=expr, right=right)
        return expr

    def parse_assignment(self) -> dict:
        arrow = self._try_arrow()
        if arrow is not None:
            return arrow
        left = self.parse_conditional()
        if self.at_punct(*ASSIGN_OPS):
            op = self.next().value
            if left["t"] not in ("Ident", "Member", "Index"):
                raise self.error(f"invalid assignment target {left['t']}")
            value = self.parse_assignment()
            return n("Assign", op=op, target=left, value=value)
        return left

    def _try_arrow(self) -> Optional[dict]:
        """Parse an arrow function if one starts here, else restore."""
        start = self.i
        is_async = False
        if self.at_kw("async") and (
                self.peek(1).type == "IDENT" or
                (self.peek(1).type == "PUNCT" and self.peek(1).value == "(")):
            # `async` on the same line followed by params
            self.next()
            is_async = True
        t = self.peek()
        if t.type == "IDENT" and self.peek(1).type == "PUNCT" \
                and self.peek(1).value == "=>":
            self.next()
            params = [n("Param", target=n("Ident", name=t.value),
                        default=None, rest=False)]
            self.eat_punct("=>")
            return self._finish_arrow(params, is_async)
        if t.type == "PUNCT" and t.value == "(":
            try:
                params = self.parse_params_paren()
                if self.at_punct("=>"):
                    self.next()
                    return self._finish_arrow(params, is_async)
            except ParseError:
                pass
            self.i = start
            return None
        self.i = start
        return None

    def _finish_arrow(self, params: list[dict], is_async: bool) -> dict:
        if self.at_punct("{"):
            body = self.parse_block()
        else:
            body = n("Block", body=[n("Return", arg=self.parse_assignment())])
        return n("Func", name=None, params=params, body=body,
                 is_async=is_async, is_arrow=True)

    def parse_conditional(self) -> dict:
        test = self.parse_nullish_or()
        if self.at_punct("?"):
            self.next()
            cons = self.parse_assignment()
            self.eat_punct(":")
            alt = self.parse_assignment()
            return n("Cond", test=test, cons=cons, alt=alt)
        return test

    def parse_nullish_or(self) -> dict:
        left = self.parse_and()
        while self.at_punct("||", "??"):
            op = self.next().value
            right = self.parse_and()
            left = n("Logical", op=op, left=left, right=right)
        return left

    def parse_and(self) -> dict:
        left = self.parse_equality()
        while self.at_punct("&&"):
            self.next()
            right = self.parse_equality()
            left = n("Logical", op="&&", left=left, right=right)
        return left

    def parse_equality(self) -> dict:
        left = self.parse_relational()
        while self.at_punct(*EQUALITY):
            op = self.next().value
            right = self.parse_relational()
            left = n("Binary", op=op, left=left, right=right)
        return left

    def parse_relational(self) -> dict:
        left = self.parse_additive()
        while self.at_punct(*RELATIONAL) or self.at_kw("instanceof", "in"):
            op = self.next().value
            right = self.parse_additive()
            left = n("Binary", op=op, left=left, right=right)
        return left

    def parse_additive(self) -> dict:
        left = self.parse_multiplicative()
        while self.at_punct(*ADDITIVE):
            op = self.next().value
            right = self.parse_multiplicative()
            left = n("Binary", op=op, left=left, right=right)
        return left

    def parse_multiplicative(self) -> dict:
        left = self.parse_unary()
        while self.at_punct(*MULTIPLICATIVE):
            op = self.next().value
            right = self.parse_unary()
            left = n("Binary", op=op, left=left, right=right)
        return left

    def parse_unary(self) -> dict:
        t = self.peek()
        if t.type == "PUNCT" and t.value in UNARY:
            self.next()
            return n("Unary", op=t.value, arg=self.parse_unary())
        if t.type == "PUNCT" and t.value in ("++", "--"):
            self.next()
            target = self.parse_unary()
            return n("Update", op=t.value, prefix=True, target=target)
        if t.type == "KEYWORD" and t.value in ("typeof", "delete", "void"):
            self.next()
            return n("Unary", op=t.value, arg=self.parse_unary())
        if t.type == "KEYWORD" and t.value == "await":
            self.next()
            return n("Await", arg=self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self) -> dict:
        expr = self.parse_call_member()
        if self.at_punct("++", "--"):
            op = self.next().value
            return n("Update", op=op, prefix=False, target=expr)
        return expr

    def parse_call_member(self) -> dict:
        if self.at_kw("new"):
            self.next()
            callee = self.parse_call_member_no_call()
            args = self.parse_args() if self.at_punct("(") else []
            expr = n("New", callee=callee, args=args)
        else:
            expr = self.parse_primary()
        return self._member_chain(expr, allow_calls=True)

    def parse_call_member_no_call(self) -> dict:
        expr = self.parse_primary()
        return self._member_chain(expr, allow_calls=False)

    def _member_chain(self, expr: dict, allow_calls: bool) -> dict:
        while True:
            if self.at_punct("."):
                self.next()
                name = self.next()
                if name.type not in ("IDENT", "KEYWORD"):
                    raise self.error("bad member name")
                expr = n("Member", obj=expr, prop=name.value)
            elif self.at_punct("["):
                self.next()
                idx = self.parse_expression()
                self.eat_punct("]")
                expr = n("Index", obj=expr, expr=idx)
            elif allow_calls and self.at_punct("("):
                expr = n("Call", callee=expr, args=self.parse_args())
            else:
                return expr

    def parse_args(self) -> list[dict]:
        self.eat_punct("(")
        args = []
        while not self.at_punct(")"):
            if self.at_punct("..."):
                self.next()
                args.append(n("Spread", arg=self.parse_assignment()))
            else:
                args.append(self.parse_assignment())
            if self.at_punct(","):
                self.next()
        self.next()
        return args

    def parse_primary(self) -> dict:
        t = self.peek()
        if t.type == "NUM":
            self.next()
            return n("Num", value=t.value)
        if t.type == "STR":
            self.next()
            return n("Str", value=t.value)
        if t.type == "REGEX":
            self.next()
            return n("Regex", source=t.value[0], flags=t.value[1])
        if t.type == "TEMPLATE":
            self.next()
            quasis = []
            for kind, val in t.value:
                if kind == "str":
                    quasis.append(("str", val))
                else:
                    quasis.append(("expr", parse_expr_source(val)))
            return n("Template", quasis=quasis)
        if t.type == "IDENT":
            self.next()
            return n("Ident", name=t.value)
        if t.type == "KEYWORD":
            kw = t.value
            if kw == "true":
                self.next()
                return n("Bool", value=True)
            if kw == "false":
                self.next()
                return n("Bool", value=False)
            if kw == "null":
                self.next()
                return n("Null")
            if kw == "this":
                self.next()
                return n("This")
            if kw == "function":
                return self.parse_function(is_async=False, as_decl=False)
            if kw == "async" and self.peek(1).type == "KEYWORD" \
                    and self.peek(1).value == "function":
                self.next()
                return self.parse_function(is_async=True, as_decl=False)
            # contextual keywords used as plain identifiers (of, async, ...)
            if kw in ("of", "async", "let"):
                self.next()
                return n("Ident", name=kw)
        if t.type == "PUNCT":
            if t.value == "(":
                self.next()
                expr = self.parse_expression()
                self.eat_punct(")")
                return expr
            if t.value == "[":
                return self.parse_array_literal()
            if t.value == "{":
                return self.parse_object_literal()
        raise self.error(f"unexpected token {t.type} {t.value!r}")

    def parse_array_literal(self) -> dict:
        self.eat_punct("[")
        elements = []
        while not self.at_punct("]"):
            if self.at_punct(","):
                self.next()
                continue
            if self.at_punct("..."):
                self.next()
                elements.append(n("Spread", arg=self.parse_assignment()))
            else:
                elements.append(self.parse_assignment())
            if self.at_punct(","):
                self.next()
        self.next()
        return n("Array", elements=elements)

    def parse_object_literal(self) -> dict:
        self.eat_punct("{")
        props = []
        while not self.at_punct("}"):
            if self.at_punct("..."):
                self.next()
                props.append(("spread", self.parse_assignment()))
            else:
                key_tok = self.next()
                if key_tok.type in ("IDENT", "KEYWORD"):
                    key = key_tok.value
                elif key_tok.type == "STR":
                    key = key_tok.value
                elif key_tok.type == "NUM":
                    key = _num_key(key_tok.value)
                else:
                    raise self.error(f"bad object key {key_tok.value!r}")
                if self.at_punct(":"):
                    self.next()
                    props.append((key, self.parse_assignment()))
                elif self.at_punct("("):
                    # method shorthand: name(args) { ... }
                    params = self.parse_params_paren()
                    body = self.parse_block()
                    props.append((key, n("Func", name=key, params=params,
                                         body=body, is_async=False,
                                         is_arrow=False)))
                else:
                    props.append((key, n("Ident", name=key)))  # shorthand
            if self.at_punct(","):
                self.next()
        self.next()
        return n("Object", props=props)


def _num_key(v: float) -> str:
    return str(int(v)) if v == int(v) else str(v)


def parse(src: str) -> dict:
    return Parser(tokenize(src)).parse_program()


def parse_expr_source(src: str) -> dict:
    p = Parser(tokenize(src))
    expr = p.parse_expression()
    if p.peek().type != "EOF":
        raise ParseError(f"trailing tokens in expression {src!r}")
    return expr

"""minijs lexer.

Produces a flat token list.  Template literals come out as one TEMPLATE
token whose value is a list of ("str", cooked) / ("expr", source) parts —
the parser re-lexes each expression source, which makes nested templates
work without lexer/parser coupling.  Regex-vs-division is disambiguated by
the previous significant token, the standard single-token-lookbehind
heuristic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


class LexError(SyntaxError):
    pass


KEYWORDS = {
    "var", "let", "const", "function", "return", "if", "else", "for", "of",
    "in", "while", "do", "break", "continue", "new", "typeof", "instanceof",
    "try", "catch", "finally", "throw", "true", "false", "null", "this",
    "async", "await", "delete", "void",
}

# longest first
PUNCTUATORS = [
    "===", "!==", "**=", "...",
    "=>", "==", "!=", "<=", ">=", "&&", "||", "??", "+=", "-=", "*=", "/=",
    "%=", "++", "--", "**",
    "{", "}", "(", ")", "[", "]", ";", ",", "<", ">", "+", "-", "*", "/",
    "%", "=", "!", "?", ":", ".", "&", "|", "^", "~",
]

# a `/` right after one of these starts a regex literal, not division
_REGEX_PRECEDERS = {
    "(", ",", "=", ":", "[", "!", "&", "|", "?", "{", "}", ";", "=>", "==",
    "===", "!=", "!==", "<", ">", "<=", ">=", "&&", "||", "??", "+", "-",
    "*", "/", "%", "+=", "-=", "*=", "/=", "%=", "...",
}
_REGEX_PRECEDER_KEYWORDS = {
    "return", "typeof", "instanceof", "new", "in", "of", "throw", "await",
    "delete", "void", "case",
}


@dataclass
class Token:
    type: str   # NUM STR TEMPLATE REGEX IDENT KEYWORD PUNCT EOF
    value: Any
    line: int


def _is_ident_start(c: str) -> bool:
    return c.isalpha() or c in "_$"


def _is_ident_part(c: str) -> bool:
    return c.isalnum() or c in "_$"


class Lexer:
    def __init__(self, src: str):
        self.src = src
        self.i = 0
        self.line = 1
        self.tokens: list[Token] = []

    def error(self, msg: str) -> LexError:
        return LexError(f"line {self.line}: {msg}")

    def _prev_significant(self) -> Token | None:
        return self.tokens[-1] if self.tokens else None

    def tokenize(self) -> list[Token]:
        src, n = self.src, len(self.src)
        while self.i < n:
            c = src[self.i]
            if c == "\n":
                self.line += 1
                self.i += 1
                continue
            if c.isspace():
                self.i += 1
                continue
            if src.startswith("//", self.i):
                j = src.find("\n", self.i)
                self.i = n if j < 0 else j
                continue
            if src.startswith("/*", self.i):
                j = src.find("*/", self.i + 2)
                if j < 0:
                    raise self.error("unterminated block comment")
                self.line += src.count("\n", self.i, j)
                self.i = j + 2
                continue
            if c == "`":
                self.tokens.append(self._template())
                continue
            if c in "'\"":
                self.tokens.append(self._string(c))
                continue
            if c.isdigit() or (c == "." and self.i + 1 < n and src[self.i + 1].isdigit()):
                self.tokens.append(self._number())
                continue
            if _is_ident_start(c):
                j = self.i + 1
                while j < n and _is_ident_part(src[j]):
                    j += 1
                word = src[self.i:j]
                self.i = j
                t = "KEYWORD" if word in KEYWORDS else "IDENT"
                self.tokens.append(Token(t, word, self.line))
                continue
            if c == "/" and self._regex_allowed():
                self.tokens.append(self._regex())
                continue
            for p in PUNCTUATORS:
                if src.startswith(p, self.i):
                    self.i += len(p)
                    self.tokens.append(Token("PUNCT", p, self.line))
                    break
            else:
                raise self.error(f"unexpected character {c!r}")
        self.tokens.append(Token("EOF", None, self.line))
        return self.tokens

    def _regex_allowed(self) -> bool:
        prev = self._prev_significant()
        if prev is None:
            return True
        if prev.type == "PUNCT":
            return prev.value in _REGEX_PRECEDERS
        if prev.type == "KEYWORD":
            return prev.value in _REGEX_PRECEDER_KEYWORDS
        return False  # after IDENT/NUM/STR/REGEX/TEMPLATE, `/` is division

    def _string(self, quote: str) -> Token:
        src, n = self.src, len(self.src)
        i = self.i + 1
        out = []
        while i < n:
            c = src[i]
            if c == quote:
                self.i = i + 1
                return Token("STR", "".join(out), self.line)
            if c == "\n":
                raise self.error("unterminated string")
            if c == "\\":
                if i + 1 >= n:
                    raise self.error("bad escape at end of input")
                out.append(self._escape(src[i + 1]))
                i += 2
                continue
            out.append(c)
            i += 1
        raise self.error("unterminated string")

    @staticmethod
    def _escape(c: str) -> str:
        return {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
                "0": "\0", "v": "\v"}.get(c, c)  # \\ \' \" \` fall through

    def _number(self) -> Token:
        src, n = self.src, len(self.src)
        i = self.i
        if src.startswith(("0x", "0X"), i):
            j = i + 2
            while j < n and src[j] in "0123456789abcdefABCDEF":
                j += 1
            self.i = j
            return Token("NUM", float(int(src[i:j], 16)), self.line)
        j = i
        while j < n and src[j].isdigit():
            j += 1
        if j < n and src[j] == ".":
            j += 1
            while j < n and src[j].isdigit():
                j += 1
        if j < n and src[j] in "eE":
            j += 1
            if j < n and src[j] in "+-":
                j += 1
            while j < n and src[j].isdigit():
                j += 1
        self.i = j
        return Token("NUM", float(src[i:j]), self.line)

    def _regex(self) -> Token:
        src, n = self.src, len(self.src)
        i = self.i + 1
        body = []
        in_class = False
        while i < n:
            c = src[i]
            if c == "\\":
                if i + 1 >= n:
                    raise self.error("bad regex escape")
                body.append(src[i:i + 2])
                i += 2
                continue
            if c == "\n":
                raise self.error("unterminated regex")
            if c == "[":
                in_class = True
            elif c == "]":
                in_class = False
            elif c == "/" and not in_class:
                j = i + 1
                while j < n and _is_ident_part(src[j]):
                    j += 1
                flags = src[i + 1:j]
                self.i = j
                return Token("REGEX", ("".join(body), flags), self.line)
            body.append(c)
            i += 1
        raise self.error("unterminated regex")

    def _template(self) -> Token:
        """Scan `...${expr}...`; expressions are captured as raw source and
        re-lexed by the parser (so nesting is handled by recursion)."""
        src, n = self.src, len(self.src)
        i = self.i + 1
        parts: list[tuple[str, str]] = []
        buf: list[str] = []
        while i < n:
            c = src[i]
            if c == "`":
                if buf:
                    parts.append(("str", "".join(buf)))
                self.i = i + 1
                return Token("TEMPLATE", parts, self.line)
            if c == "\\":
                if i + 1 >= n:
                    raise self.error("bad escape in template")
                buf.append(self._escape(src[i + 1]))
                i += 2
                continue
            if c == "\n":
                self.line += 1
                buf.append(c)
                i += 1
                continue
            if src.startswith("${", i):
                if buf:
                    parts.append(("str", "".join(buf)))
                    buf = []
                j = self._scan_template_expr(i + 2)
                parts.append(("expr", src[i + 2:j]))
                i = j + 1  # past the closing }
                continue
            buf.append(c)
            i += 1
        raise self.error("unterminated template literal")

    def _scan_template_expr(self, start: int) -> int:
        """Index of the `}` closing a ${...}, skipping nested braces,
        strings, and nested templates."""
        src, n = self.src, len(self.src)
        depth = 0
        i = start
        while i < n:
            c = src[i]
            if c == "\\":
                i += 2
                continue
            if c in "'\"":
                q = c
                i += 1
                while i < n and src[i] != q:
                    i += 2 if src[i] == "\\" else 1
                i += 1
                continue
            if c == "`":
                # nested template: recurse through its own ${} structure
                i += 1
                while i < n and src[i] != "`":
                    if src[i] == "\\":
                        i += 2
                        continue
                    if src.startswith("${", i):
                        i = self._scan_template_expr(i + 2) + 1
                        continue
                    if src[i] == "\n":
                        self.line += 1
                    i += 1
                i += 1
                continue
            if c == "{":
                depth += 1
            elif c == "}":
                if depth == 0:
                    return i
                depth -= 1
            elif c == "\n":
                self.line += 1
            i += 1
        raise self.error("unterminated ${...} in template")


def tokenize(src: str) -> list[Token]:
    return Lexer(src).tokenize()

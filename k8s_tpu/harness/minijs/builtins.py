"""minijs standard library: globals (JSON, Object, Math, console, Promise,
Set, Error, Number/String/Boolean, parseInt/parseFloat) and the per-type
method dispatch used by the interpreter's member access."""

from __future__ import annotations

import math
import re
from typing import Optional

from k8s_tpu.harness.minijs.interp import (
    UNDEFINED,
    Interpreter,
    JSArray,
    JSException,
    JSFunction,
    JSObject,
    JSPromise,
    JSRegExp,
    JSSet,
    NativeFunction,
    format_number,
    js_to_number,
    js_to_string,
    js_truthy,
    json_parse,
    json_stringify,
    make_error,
    strict_equals,
)


def _nf(fn, name=""):
    return NativeFunction(fn, name)


def install_globals(interp: Interpreter) -> None:
    g = interp.define

    # console ------------------------------------------------------------
    console = JSObject()
    interp.console_lines: list[str] = []

    def _log(*args):
        interp.console_lines.append(" ".join(js_to_string(a) for a in args))
        return UNDEFINED

    for name in ("log", "warn", "error", "info", "debug"):
        console[name] = _nf(_log, name)
    g("console", console)

    # JSON ---------------------------------------------------------------
    json_obj = JSObject()
    json_obj["stringify"] = _nf(
        lambda value=UNDEFINED, replacer=None, space=0.0:
            json_stringify(value, int(js_to_number(space) or 0)),
        "stringify")
    json_obj["parse"] = _nf(
        lambda text=UNDEFINED: json_parse(js_to_string(text)), "parse")
    g("JSON", json_obj)

    # Object -------------------------------------------------------------
    obj_ns = JSObject()
    obj_ns["keys"] = _nf(
        lambda o=UNDEFINED: JSArray(o.keys()) if isinstance(o, JSObject)
        else JSArray(format_number(float(i)) for i in range(len(o)))
        if isinstance(o, JSArray) else JSArray(), "keys")
    obj_ns["values"] = _nf(
        lambda o=UNDEFINED: JSArray(o.values()) if isinstance(o, JSObject)
        else JSArray(o) if isinstance(o, JSArray) else JSArray(), "values")
    obj_ns["entries"] = _nf(
        lambda o=UNDEFINED: JSArray(
            JSArray([k, v]) for k, v in o.items())
        if isinstance(o, JSObject) else JSArray(), "entries")

    def _assign(target=UNDEFINED, *sources):
        for s in sources:
            if isinstance(s, JSObject):
                target.update(s)
        return target

    obj_ns["assign"] = _nf(_assign, "assign")
    obj_ns["fromEntries"] = _nf(
        lambda pairs=UNDEFINED: JSObject(
            (js_to_string(p[0]), p[1]) for p in pairs), "fromEntries")
    g("Object", obj_ns)

    # Array --------------------------------------------------------------
    arr_ns = JSObject()
    arr_ns["isArray"] = _nf(lambda v=UNDEFINED: isinstance(v, JSArray),
                            "isArray")

    def _array_from(v=UNDEFINED, map_fn=None):
        items = JSArray(interp._iterate(v)) if not isinstance(v, JSObject) \
            else JSArray(
                interp.get_index(v, float(i))
                for i in range(int(js_to_number(v.get("length", 0.0)))))
        if map_fn is not None and map_fn is not UNDEFINED:
            items = JSArray(interp.call(map_fn, [x, float(i)])
                            for i, x in enumerate(items))
        return items

    arr_ns["from"] = _nf(_array_from, "from")
    g("Array", arr_ns)

    # Math ---------------------------------------------------------------
    math_obj = JSObject()
    math_obj["floor"] = _nf(lambda v=UNDEFINED: float(math.floor(js_to_number(v))))
    math_obj["ceil"] = _nf(lambda v=UNDEFINED: float(math.ceil(js_to_number(v))))
    math_obj["round"] = _nf(
        lambda v=UNDEFINED: float(math.floor(js_to_number(v) + 0.5)))
    math_obj["abs"] = _nf(lambda v=UNDEFINED: abs(js_to_number(v)))
    math_obj["min"] = _nf(lambda *a: min((js_to_number(x) for x in a),
                                         default=float("inf")))
    math_obj["max"] = _nf(lambda *a: max((js_to_number(x) for x in a),
                                         default=float("-inf")))
    math_obj["trunc"] = _nf(lambda v=UNDEFINED: float(math.trunc(js_to_number(v))))
    math_obj["sqrt"] = _nf(lambda v=UNDEFINED: math.sqrt(js_to_number(v)))
    math_obj["pow"] = _nf(lambda a=UNDEFINED, b=UNDEFINED:
                          js_to_number(a) ** js_to_number(b))
    g("Math", math_obj)

    # primitives / conversions -------------------------------------------
    number_fn = _nf(lambda v=0.0: js_to_number(v), "Number")
    number_fn.js_get = lambda prop: {  # type: ignore[attr-defined]
        "isInteger": _nf(lambda v=UNDEFINED: isinstance(v, float)
                         and not math.isnan(v) and not math.isinf(v)
                         and v == int(v)),
        "isFinite": _nf(lambda v=UNDEFINED: isinstance(v, float)
                        and math.isfinite(v)),
        "isNaN": _nf(lambda v=UNDEFINED: isinstance(v, float)
                     and math.isnan(v)),
        "parseFloat": _nf(_parse_float),
        "parseInt": _nf(_parse_int),
        "MAX_SAFE_INTEGER": float(2**53 - 1),
    }.get(prop, UNDEFINED)
    g("Number", number_fn)
    g("String", _nf(lambda v="": js_to_string(v), "String"))
    g("Boolean", _nf(lambda v=UNDEFINED: js_truthy(v), "Boolean"))
    g("parseInt", _nf(_parse_int, "parseInt"))
    g("parseFloat", _nf(_parse_float, "parseFloat"))
    g("isNaN", _nf(lambda v=UNDEFINED: math.isnan(js_to_number(v)), "isNaN"))

    # Error constructors --------------------------------------------------
    for name in ("Error", "TypeError", "RangeError", "SyntaxError"):
        g(name, _error_ctor(name))

    # Set -----------------------------------------------------------------
    set_ctor = _nf(lambda it=UNDEFINED: JSSet(
        () if it is UNDEFINED or it is None else interp._iterate(it)), "Set")
    set_ctor.js_construct = lambda args: JSSet(  # type: ignore[attr-defined]
        () if not args or args[0] is UNDEFINED or args[0] is None
        else interp._iterate(args[0]))
    g("Set", set_ctor)

    # Promise -------------------------------------------------------------
    promise_ns = JSObject()

    def _resolved(v=UNDEFINED):
        p = JSPromise(interp)
        p.resolve(v)
        return p

    def _rejected(v=UNDEFINED):
        p = JSPromise(interp)
        p.reject(v)
        return p

    def _all(items=UNDEFINED):
        arr = list(interp._iterate(items))
        out = JSPromise(interp)
        results = JSArray([UNDEFINED] * len(arr))
        remaining = [len(arr)]
        if not arr:
            out.resolve(results)
            return out
        for i, item in enumerate(arr):
            p = item if isinstance(item, JSPromise) else _resolved(item)

            def ok(v, i=i):
                results[i] = v
                remaining[0] -= 1
                if remaining[0] == 0:
                    out.resolve(results)

            p.then_native(ok, out.reject)
        return out

    promise_ns["resolve"] = _nf(_resolved, "resolve")
    promise_ns["reject"] = _nf(_rejected, "reject")
    promise_ns["all"] = _nf(_all, "all")

    def _promise_construct(args):
        executor = args[0] if args else UNDEFINED
        p = JSPromise(interp)
        interp.call(executor, [
            _nf(lambda v=UNDEFINED: p.resolve(v), "resolve"),
            _nf(lambda v=UNDEFINED: p.reject(v), "reject"),
        ])
        return p

    promise_ns.js_construct = _promise_construct  # type: ignore[attr-defined]
    g("Promise", promise_ns)

    g("globalThis", _GlobalThis(interp))


class _GlobalThis:
    def __init__(self, interp: Interpreter):
        self._interp = interp

    def js_get(self, name):
        if self._interp.globals.has(name):
            return self._interp.globals.lookup(name)
        return UNDEFINED

    def js_set(self, name, value):
        self._interp.globals.declare(name, value)


def _error_ctor(name: str) -> NativeFunction:
    def ctor(message=UNDEFINED):
        return make_error(
            "" if message is UNDEFINED else js_to_string(message), name=name)

    fn = _nf(ctor, name)
    fn.js_construct = lambda args: ctor(*args[:1])  # type: ignore[attr-defined]
    return fn


def _parse_int(v=UNDEFINED, radix=UNDEFINED):
    s = js_to_string(v).strip()
    base = int(js_to_number(radix)) if radix is not UNDEFINED and \
        not math.isnan(js_to_number(radix)) else 10
    m = re.match(r"[+-]?[0-9a-zA-Z]+", s)
    if not m:
        return float("nan")
    text = m.group(0)
    try:
        # trim until parseable in base (JS stops at the first bad char)
        while text and text not in "+-":
            try:
                return float(int(text, base))
            except ValueError:
                text = text[:-1]
        return float("nan")
    except ValueError:
        return float("nan")


def _parse_float(v=UNDEFINED):
    s = js_to_string(v).strip()
    m = re.match(r"[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?", s)
    return float(m.group(0)) if m else float("nan")


# ---------------------------------------------------------------------------
# per-type methods
# ---------------------------------------------------------------------------

def string_method(interp: Interpreter, s: str, prop: str) -> Optional[NativeFunction]:
    def replace(pattern=UNDEFINED, repl=UNDEFINED):
        if isinstance(pattern, JSRegExp):
            count = 0 if pattern.global_ else 1
            if callable(repl) or isinstance(repl, (JSFunction, NativeFunction)):
                return pattern.pattern.sub(
                    lambda m: js_to_string(
                        interp.call(repl, [m.group(0),
                                           *[g if g is not None else UNDEFINED
                                             for g in m.groups()]])),
                    s, count=count)
            text = js_to_string(repl)
            return pattern.pattern.sub(lambda m: text, s, count=count)
        needle = js_to_string(pattern)
        text = js_to_string(repl)
        return s.replace(needle, text, 1)

    def split(sep=UNDEFINED, limit=UNDEFINED):
        if sep is UNDEFINED:
            return JSArray([s])
        if isinstance(sep, JSRegExp):
            parts = sep.pattern.split(s)
            # drop capture groups the Python split interleaves
            if sep.pattern.groups:
                parts = parts[::sep.pattern.groups + 1]
            return JSArray(parts)
        sep = js_to_string(sep)
        if sep == "":
            return JSArray(list(s))
        return JSArray(s.split(sep))

    def _idx(v, default):
        if v is UNDEFINED:
            return default
        i = int(js_to_number(v))
        return max(len(s) + i, 0) if i < 0 else min(i, len(s))

    table = {
        "replace": replace,
        "replaceAll": lambda pattern=UNDEFINED, repl=UNDEFINED:
            s.replace(js_to_string(pattern), js_to_string(repl))
            if not isinstance(pattern, JSRegExp) else replace(pattern, repl),
        "split": split,
        "trim": lambda: s.strip(),
        "trimStart": lambda: s.lstrip(),
        "trimEnd": lambda: s.rstrip(),
        "includes": lambda needle=UNDEFINED: js_to_string(needle) in s,
        "indexOf": lambda needle=UNDEFINED:
            float(s.find(js_to_string(needle))),
        "lastIndexOf": lambda needle=UNDEFINED:
            float(s.rfind(js_to_string(needle))),
        "startsWith": lambda needle=UNDEFINED:
            s.startswith(js_to_string(needle)),
        "endsWith": lambda needle=UNDEFINED: s.endswith(js_to_string(needle)),
        "toLowerCase": lambda: s.lower(),
        "toUpperCase": lambda: s.upper(),
        "slice": lambda a=UNDEFINED, b=UNDEFINED: s[_idx(a, 0):_idx(b, len(s))],
        "substring": lambda a=UNDEFINED, b=UNDEFINED:
            s[min(_idx(a, 0), _idx(b, len(s))):max(_idx(a, 0), _idx(b, len(s)))],
        "charAt": lambda i=0.0: s[int(js_to_number(i))]
            if 0 <= int(js_to_number(i)) < len(s) else "",
        "charCodeAt": lambda i=0.0: float(ord(s[int(js_to_number(i))]))
            if 0 <= int(js_to_number(i)) < len(s) else float("nan"),
        "concat": lambda *a: s + "".join(js_to_string(x) for x in a),
        "repeat": lambda nrep=0.0: s * int(js_to_number(nrep)),
        "padStart": lambda width=0.0, fill=" ":
            _pad(s, int(js_to_number(width)), js_to_string(fill), True),
        "padEnd": lambda width=0.0, fill=" ":
            _pad(s, int(js_to_number(width)), js_to_string(fill), False),
        "match": lambda pattern=UNDEFINED: _str_match(s, pattern),
        "toString": lambda: s,
    }
    fn = table.get(prop)
    return _nf(fn, prop) if fn is not None else None


def _pad(s: str, width: int, fill: str, start: bool) -> str:
    if len(s) >= width or not fill:
        return s
    pad = (fill * width)[:width - len(s)]
    return pad + s if start else s + pad


def _str_match(s: str, pattern):
    if not isinstance(pattern, JSRegExp):
        pattern = JSRegExp(js_to_string(pattern), "")
    if pattern.global_:
        return JSArray(m.group(0) for m in pattern.pattern.finditer(s)) \
            or None
    m = pattern.pattern.search(s)
    if m is None:
        return None
    out = JSArray([m.group(0), *[g if g is not None else UNDEFINED
                                 for g in m.groups()]])
    return out


def array_method(interp: Interpreter, arr: JSArray, prop: str) -> Optional[NativeFunction]:
    call = interp.call

    def _cb(fn, x, i):
        return call(fn, [x, float(i), arr])

    def splice(start=0.0, delete_count=UNDEFINED, *items):
        i = int(js_to_number(start))
        if i < 0:
            i = max(len(arr) + i, 0)
        dc = len(arr) - i if delete_count is UNDEFINED \
            else max(0, int(js_to_number(delete_count)))
        removed = JSArray(arr[i:i + dc])
        arr[i:i + dc] = list(items)
        return removed

    def sort(cmp=UNDEFINED):
        import functools
        if cmp is UNDEFINED:
            arr.sort(key=js_to_string)
        else:
            arr.sort(key=functools.cmp_to_key(
                lambda a, b: (lambda r: (r > 0) - (r < 0))(
                    js_to_number(call(cmp, [a, b])))))
        return arr

    def reduce(fn=UNDEFINED, *init):
        if not arr and not init:
            raise JSException(make_error(
                "Reduce of empty array with no initial value",
                name="TypeError"))
        items = list(arr)
        if init:
            acc = init[0]
            start = 0
        else:
            acc = items[0]
            start = 1
        for i in range(start, len(items)):
            acc = call(fn, [acc, items[i], float(i), arr])
        return acc

    def index_of(needle=UNDEFINED):
        for i, x in enumerate(arr):
            if strict_equals(x, needle):
                return float(i)
        return -1.0

    def flat(depth=1.0):
        d = int(js_to_number(depth))

        def go(a, d):
            out = []
            for x in a:
                if isinstance(x, JSArray) and d > 0:
                    out.extend(go(x, d - 1))
                else:
                    out.append(x)
            return out
        return JSArray(go(arr, d))

    table = {
        "push": lambda *items: (arr.extend(items), float(len(arr)))[1],
        "pop": lambda: arr.pop() if arr else UNDEFINED,
        "shift": lambda: arr.pop(0) if arr else UNDEFINED,
        "unshift": lambda *items: (arr.__setitem__(
            slice(0, 0), list(items)), float(len(arr)))[1],
        "map": lambda fn=UNDEFINED: JSArray(
            _cb(fn, x, i) for i, x in enumerate(list(arr))),
        "filter": lambda fn=UNDEFINED: JSArray(
            x for i, x in enumerate(list(arr)) if js_truthy(_cb(fn, x, i))),
        "forEach": lambda fn=UNDEFINED: (
            [_cb(fn, x, i) for i, x in enumerate(list(arr))], UNDEFINED)[1],
        "find": lambda fn=UNDEFINED: next(
            (x for i, x in enumerate(list(arr)) if js_truthy(_cb(fn, x, i))),
            UNDEFINED),
        "findIndex": lambda fn=UNDEFINED: next(
            (float(i) for i, x in enumerate(list(arr))
             if js_truthy(_cb(fn, x, i))), -1.0),
        "some": lambda fn=UNDEFINED: any(
            js_truthy(_cb(fn, x, i)) for i, x in enumerate(list(arr))),
        "every": lambda fn=UNDEFINED: all(
            js_truthy(_cb(fn, x, i)) for i, x in enumerate(list(arr))),
        "join": lambda sep=",": js_to_string(sep).join(
            "" if x is UNDEFINED or x is None else js_to_string(x)
            for x in arr),
        "indexOf": index_of,
        "includes": lambda needle=UNDEFINED: any(
            strict_equals(x, needle) for x in arr),
        "slice": lambda a=UNDEFINED, b=UNDEFINED: JSArray(
            arr[_slice_idx(arr, a, 0):_slice_idx(arr, b, len(arr))]),
        "splice": splice,
        "concat": lambda *others: JSArray(
            list(arr) + [y for o in others for y in
                         (list(o) if isinstance(o, JSArray) else [o])]),
        "reverse": lambda: (arr.reverse(), arr)[1],
        "sort": sort,
        "reduce": reduce,
        "flat": flat,
        "flatMap": lambda fn=UNDEFINED: JSArray(
            y for i, x in enumerate(list(arr))
            for y in (lambda r: list(r) if isinstance(r, JSArray) else [r])(
                _cb(fn, x, i))),
        "keys": lambda: JSArray(float(i) for i in range(len(arr))),
        "entries": lambda: JSArray(
            JSArray([float(i), x]) for i, x in enumerate(arr)),
        "toString": lambda: js_to_string(arr),
    }
    fn = table.get(prop)
    return _nf(fn, prop) if fn is not None else None


def _slice_idx(arr, v, default):
    if v is UNDEFINED:
        return default
    i = int(js_to_number(v))
    return max(len(arr) + i, 0) if i < 0 else min(i, len(arr))


def object_method(interp: Interpreter, obj: JSObject, prop: str):
    if prop == "hasOwnProperty":
        return _nf(lambda k=UNDEFINED: js_to_string(k) in obj,
                   "hasOwnProperty")
    if prop == "toString":
        return _nf(lambda: js_to_string(obj), "toString")
    return None


def promise_method(interp: Interpreter, p: JSPromise, prop: str):
    if prop == "then":
        def then(on_ok=UNDEFINED, on_err=UNDEFINED):
            ok = (lambda v: interp.call(on_ok, [v])) \
                if on_ok is not UNDEFINED and on_ok is not None else None
            err = (lambda v: interp.call(on_err, [v])) \
                if on_err is not UNDEFINED and on_err is not None else None
            return p.then_native(ok, err)
        return _nf(then, "then")
    if prop == "catch":
        def catch(on_err=UNDEFINED):
            err = (lambda v: interp.call(on_err, [v])) \
                if on_err is not UNDEFINED else None
            return p.then_native(None, err)
        return _nf(catch, "catch")
    if prop == "finally":
        def finally_(cb=UNDEFINED):
            def run_ok(v):
                interp.call(cb, [])
                return v

            def run_err(e):
                interp.call(cb, [])
                raise JSException(e)
            return p.then_native(run_ok, run_err)
        return _nf(finally_, "finally")
    return UNDEFINED


def set_method(interp: Interpreter, s: JSSet, prop: str):
    if prop == "size":
        return float(len(s.items))
    table = {
        "add": lambda v=UNDEFINED: s.add(v),
        "has": lambda v=UNDEFINED: s.has(v),
        "delete": lambda v=UNDEFINED: _set_delete(s, v),
        "forEach": lambda fn=UNDEFINED: (
            [interp.call(fn, [x, x, s]) for x in list(s.items)], UNDEFINED)[1],
        "clear": lambda: (s.items.clear(), UNDEFINED)[1],
    }
    fn = table.get(prop)
    return _nf(fn, prop) if fn is not None else UNDEFINED


def _set_delete(s: JSSet, v) -> bool:
    for i, x in enumerate(s.items):
        if strict_equals(x, v):
            del s.items[i]
            return True
    return False


def regexp_method(interp: Interpreter, r: JSRegExp, prop: str):
    if prop == "source":
        return r.source
    if prop == "flags":
        return r.flags
    if prop == "test":
        return _nf(lambda s=UNDEFINED:
                   r.pattern.search(js_to_string(s)) is not None, "test")
    if prop == "exec":
        def exec_(s=UNDEFINED):
            m = r.pattern.search(js_to_string(s))
            if m is None:
                return None
            return JSArray([m.group(0),
                            *[g if g is not None else UNDEFINED
                              for g in m.groups()]])
        return _nf(exec_, "exec")
    return UNDEFINED


def number_method(interp: Interpreter, f: float, prop: str):
    table = {
        "toFixed": lambda digits=0.0:
            f"{f:.{int(js_to_number(digits))}f}",
        "toString": lambda: format_number(f),
        "toPrecision": lambda digits=UNDEFINED: format_number(f)
            if digits is UNDEFINED else f"{f:.{int(js_to_number(digits))}g}",
    }
    fn = table.get(prop)
    return _nf(fn, prop) if fn is not None else UNDEFINED

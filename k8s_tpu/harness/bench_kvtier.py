"""Tiered KV memory hierarchy bench (ISSUE 17): host-RAM spill tier
vs evict-recompute, fingerprint-dedup migration, and fixed-seed
identity through every tier crossing.

    python -m k8s_tpu.harness.bench_kvtier

Three measured stages, all CPU-provable on the tiny bench_serve model:

- **spill throughput**: one engine, a prompt corpus whose distinct
  prefix blocks total ~10x the device pool's prefix headroom, replayed
  for several rounds (identical traffic and seed in both arms).  With
  ``spill_mb`` set, evicted ``PrefixTree`` leaves demote to host RAM
  and re-promote through the graft scatter on the next tree walk; with
  it unset, eviction discards and every revisit re-prefills.  Embedded
  assertions: post-warmup tokens/s AND prefix hit rate strictly beat
  the evict-recompute baseline, and the spill arm actually demoted and
  promoted blocks (a corpus that never pressures the pool proves
  nothing — retune it).
- **spill identity**: an int8-KV-pool engine (spill stores int8 pools
  bit-exact; fp pools int8-quantize and are documented-lossy like the
  wire) answers each lane — greedy, sampled, top-k, speculative — then
  a filler flood forces the lane's blocks through demote, and the
  re-ask must return token-identical output THROUGH the promote path
  (per-lane ``spill_promotions`` must move, or the flood never
  demoted).
- **dedup migration storm**: two real LmServers over real sockets
  (prefill -> decode, the ISSUE 15 plane), a repeated-prefix storm of
  ``kv_dest`` migrations.  The fingerprint handshake must skip blocks
  the receiver already holds (sender-side
  ``serve_kvxfer_dedup_blocks_skipped_total`` > 0, estimated wire
  bytes saved > 0), and each lane's answer through a DEDUPED migration
  must match the local single-engine oracle.

Artifact contract: one JSON line (``bench_kvtier.json``); on assertion
failure the artifact still lands with a ``failures`` field attached.
Wired into the non-gating bench_smoke tier as ``bench_operator
--kvtier``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
import urllib.request

import numpy as np

log = logging.getLogger(__name__)

LANES = ("greedy", "sampled", "top_k", "spec")


def _lane_kwargs(lane: str) -> dict:
    return {
        "greedy": {},
        "sampled": {"temperature": 1.0, "seed": 1234},
        "top_k": {"temperature": 0.7, "top_k": 7, "seed": 77},
        "spec": {"speculative": 4},
    }[lane]


def _prompt(rank: int, n: int) -> np.ndarray:
    return np.asarray([(rank * 37 + i * 11 + 5) % 256 for i in range(n)],
                      np.int32)


def _post(port: int, body: dict, timeout: float = 180.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _spill_arm(config, params, *, spill_mb, corpus: int,
               prompt_len: int, rounds: int, prefix_blocks: int,
               max_new: int) -> dict:
    """One throughput arm: the same corpus replay with the spill tier
    on (``spill_mb``) or off (None).  Warmup round builds every chain
    cold; an unmeasured settle round then pays the arm's remaining
    one-time compiles (tail-bucket prefill, the promote graft shape)
    so the measured rounds compare steady states, not compile queues."""
    from k8s_tpu.models.engine import Engine

    eng = Engine(config, params, slots=2, queue_limit=64,
                 block_size=16, prefix_blocks=prefix_blocks,
                 spill_mb=spill_mb)
    try:
        prompts = [_prompt(r, prompt_len) for r in range(corpus)]

        def replay() -> int:
            emitted = 0
            for p in prompts:
                emitted += len(eng.submit(p, max_new))
            return emitted

        replay()  # warmup: every chain cold, bucket compiles
        replay()  # settle: promote/tail-shapes compile unmeasured
        s0 = eng.stats()
        t0 = time.monotonic()
        tokens = sum(replay() for _ in range(rounds))
        wall = time.monotonic() - t0
        s1 = eng.stats()
        submitted = rounds * corpus * prompt_len
        saved = s1["prefix_tokens_saved"] - s0["prefix_tokens_saved"]
        return {
            "spill_mb": spill_mb,
            "rounds": rounds,
            "corpus": corpus,
            "wall_s": round(wall, 3),
            "tokens": tokens,
            "tokens_per_s": round(tokens / wall, 2) if wall else None,
            "prefix_hit_rate": round(saved / submitted, 4),
            "prefix_tokens_saved": int(saved),
            "spill_demotions": int(s1["spill_demotions"]),
            "spill_promotions": int(s1["spill_promotions"]),
            "spill_blocks": int(s1["spill_blocks"]),
            "spill_bytes": int(s1["spill_bytes"]),
            "tree_evictions": int(s1["tree_evictions"]),
        }
    finally:
        eng.shutdown()


def _spill_identity(config, params, *, prompt_len: int, max_new: int,
                    failures: list) -> dict:
    """Fixed-seed identity through demote -> promote on every lane,
    on an int8 KV pool (the bit-exact tier: spill stores int8 pools
    raw — fp pools take the documented-lossy int8 round trip instead,
    exactly like the migration wire)."""
    from k8s_tpu.models.engine import Engine

    cfg8 = dataclasses.replace(config, kv_cache_dtype="int8")
    eng = Engine(cfg8, params, slots=2, queue_limit=32, block_size=16,
                 prefix_blocks=6, spill_mb=32)
    out: dict = {}
    try:
        prompts = {lane: _prompt(500 + i, prompt_len)
                   for i, lane in enumerate(LANES)}
        refs = {lane: eng.submit(prompts[lane], max_new,
                                 **_lane_kwargs(lane))
                for lane in LANES}
        # filler flood: enough distinct chains to push every lane's
        # blocks out of the tree (and into the spill tier)
        for r in range(8):
            eng.submit(_prompt(900 + r, prompt_len), 2)
        if eng.stats()["spill_demotions"] < 1:
            failures.append(
                "spill identity: the filler flood never demoted a "
                "block — the pool is too large for the flood, retune")
        for lane in LANES:
            before = eng.stats()["spill_promotions"]
            got = eng.submit(prompts[lane], max_new,
                             **_lane_kwargs(lane))
            promoted = eng.stats()["spill_promotions"] - before
            ok = got == refs[lane]
            out[lane] = {"ok": ok, "promoted_blocks": int(promoted)}
            if promoted < 1:
                failures.append(
                    f"spill identity [{lane}]: the re-ask never "
                    "promoted from the spill tier (blocks were still "
                    "in-tree), so this lane proved nothing — retune")
            if not ok:
                failures.append(
                    f"spill identity [{lane}]: fixed-seed output "
                    f"through demote->promote differs from the cold "
                    f"answer (ref {refs[lane][:6]}... vs got "
                    f"{got[:6]}...): the spill tier changed the math")
        return out
    finally:
        eng.shutdown()


def _dedup_storm(config, params, *, base_len: int, tail_len: int,
                 storm: int, max_new: int, failures: list) -> dict:
    """Repeated-prefix migration storm + per-lane identity through a
    DEDUPED migration, on two real LmServers over real sockets."""
    from k8s_tpu.models import server as server_mod
    from k8s_tpu.models.engine import Engine
    from k8s_tpu.util import metrics as metrics_mod

    # local oracle first (torn down before the servers spin up)
    base = _prompt(7, base_len)
    lane_prompts = {
        lane: np.concatenate([base, _prompt(700 + i, tail_len)])
        for i, lane in enumerate(LANES)}
    ref_eng = Engine(config, params, slots=2, queue_limit=16,
                     block_size=16)
    try:
        refs = {lane: ref_eng.submit(lane_prompts[lane], max_new,
                                     **_lane_kwargs(lane))
                for lane in LANES}
    finally:
        ref_eng.shutdown()

    sender = server_mod.LmServer(
        config=config, params=params, slots=4, queue_limit=64,
        role="prefill", registry=metrics_mod.Registry())
    receiver = server_mod.LmServer(
        config=config, params=params, slots=4, queue_limit=64,
        role="decode", kvxfer_port=0, registry=metrics_mod.Registry())
    httpd = server_mod.serve(sender)
    port = httpd.server_address[1]
    kv_dest = f"127.0.0.1:{receiver._kv_receiver.port}"
    try:
        # warm both engines' programs on a chain DISJOINT from the
        # storm's shared base, so the storm's first migration is the
        # genuinely cold one
        warm = [int(t) for t in _prompt(999, base_len + tail_len)]
        _post(port, {"tokens": warm, "max_new_tokens": max_new})
        _post(port, {"tokens": warm, "max_new_tokens": max_new,
                     "kv_dest": kv_dest})

        skipped0 = sender.metrics["kvxfer_dedup_skipped"].value
        for r in range(storm):
            tokens = [int(t) for t in
                      np.concatenate([base, _prompt(800 + r, tail_len)])]
            _post(port, {"tokens": tokens, "max_new_tokens": max_new,
                         "kv_dest": kv_dest})
        skipped = int(sender.metrics["kvxfer_dedup_skipped"].value
                      - skipped0)
        # estimated wire bytes per block, read off the sender's own
        # cached chain (the same arrays a full frame would ship)
        manifest = sender.engine.fetch_prefix(base)
        if manifest and manifest["n_blocks"]:
            per_block = sum(a.nbytes
                            for a in manifest["blocks"].values()) \
                / manifest["n_blocks"]
        else:
            per_block = 0.0
        bytes_saved = int(skipped * per_block)
        if skipped < 1:
            failures.append(
                "dedup storm: the fingerprint handshake never skipped "
                "a block across a repeated-prefix migration storm")
        elif bytes_saved < 1:
            failures.append(
                "dedup storm: blocks were skipped but the estimated "
                "wire bytes saved is zero — the block footprint "
                "estimate is broken")

        identity: dict = {}
        for lane in LANES:
            before = receiver.engine.stats()["kv_blocks_deduped"]
            got = _post(port, {
                "tokens": [int(t) for t in lane_prompts[lane]],
                "max_new_tokens": max_new,
                **_lane_kwargs(lane), "kv_dest": kv_dest})["tokens"]
            deduped = receiver.engine.stats()["kv_blocks_deduped"] \
                - before
            ok = got == refs[lane]
            identity[lane] = {"ok": ok,
                              "deduped_blocks": int(deduped)}
            if deduped < 1:
                failures.append(
                    f"migration identity [{lane}]: the migration was "
                    "never deduped (the storm should have seeded the "
                    "receiver's tree with the shared base) — this "
                    "lane proved nothing")
            if not ok:
                failures.append(
                    f"migration identity [{lane}]: fixed-seed output "
                    f"through a deduped migration differs from local "
                    f"(local {refs[lane][:6]}... vs routed "
                    f"{got[:6]}...): dedup changed the math")
        return {
            "storm_requests": storm,
            "skipped_blocks": skipped,
            "bytes_saved_est": bytes_saved,
            "wire_bytes_per_block_est": int(per_block),
            "receiver_blocks_deduped": int(
                receiver.engine.stats()["kv_blocks_deduped"]),
            "identity": identity,
        }
    finally:
        httpd.shutdown()
        sender.close()
        receiver.close()


def run_bench(corpus: int = 24, rounds: int = 3, prompt_len: int = 96,
              prefix_blocks: int = 12, spill_mb: int = 16,
              max_new: int = 4, storm: int = 6, hidden: int = 256,
              layers: int = 2) -> dict:
    from k8s_tpu.harness.bench_serve import build_model

    failures: list[str] = []
    config, params = build_model(0, hidden=hidden, layers=layers)

    arms = {
        "spill": _spill_arm(config, params, spill_mb=spill_mb,
                            corpus=corpus, prompt_len=prompt_len,
                            rounds=rounds, prefix_blocks=prefix_blocks,
                            max_new=max_new),
        "baseline": _spill_arm(config, params, spill_mb=None,
                               corpus=corpus, prompt_len=prompt_len,
                               rounds=rounds,
                               prefix_blocks=prefix_blocks,
                               max_new=max_new),
    }
    sp, bl = arms["spill"], arms["baseline"]
    if sp["spill_demotions"] < 1 or sp["spill_promotions"] < 1:
        failures.append(
            "spill arm never demoted/promoted "
            f"({sp['spill_demotions']}/{sp['spill_promotions']}): the "
            "corpus does not pressure the pool, the bench proves "
            "nothing — retune it")
    if not (sp["tokens_per_s"] and bl["tokens_per_s"]
            and sp["tokens_per_s"] > bl["tokens_per_s"]):
        failures.append(
            f"spill tokens/s ({sp['tokens_per_s']}) does not strictly "
            f"beat evict-recompute ({bl['tokens_per_s']}) on the same "
            "traffic: promoting from host RAM lost to re-prefilling")
    if not sp["prefix_hit_rate"] > bl["prefix_hit_rate"]:
        failures.append(
            f"spill post-warmup prefix hit rate "
            f"({sp['prefix_hit_rate']}) does not strictly beat the "
            f"baseline ({bl['prefix_hit_rate']})")

    spill_identity = _spill_identity(config, params,
                                     prompt_len=80, max_new=8,
                                     failures=failures)
    dedup = _dedup_storm(config, params, base_len=64, tail_len=16,
                         storm=storm, max_new=8, failures=failures)

    result = {
        "metric": "kvtier_spill_speedup",
        "value": round(sp["tokens_per_s"] / bl["tokens_per_s"], 3)
        if sp["tokens_per_s"] and bl["tokens_per_s"] else None,
        "unit": "x_tokens_per_s_vs_evict_recompute",
        "model": {"hidden": hidden, "layers": layers},
        "workload": {"corpus": corpus, "rounds": rounds,
                     "prompt_len": prompt_len,
                     "prefix_blocks": prefix_blocks,
                     "spill_mb": spill_mb, "max_new": max_new,
                     "storm": storm},
        "spill": sp,
        "baseline": bl,
        "spill_identity": spill_identity,
        "dedup": dedup,
    }
    if failures:
        result["failures"] = failures
        err = RuntimeError("kvtier bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--corpus", type=int, default=24)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--prompt-len", type=int, default=96)
    p.add_argument("--prefix-blocks", type=int, default=12)
    p.add_argument("--spill-mb", type=int, default=16)
    p.add_argument("--storm", type=int, default=6)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)

    def _write(payload: dict) -> None:
        line = json.dumps(payload)
        print(line)
        if args.out:
            import os

            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                f.write(line + "\n")

    try:
        result = run_bench(
            corpus=args.corpus, rounds=args.rounds,
            prompt_len=args.prompt_len,
            prefix_blocks=args.prefix_blocks, spill_mb=args.spill_mb,
            storm=args.storm, hidden=args.hidden, layers=args.layers)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write(partial)
        raise
    _write(result)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

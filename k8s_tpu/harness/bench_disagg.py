"""Disaggregated prefill/decode serving bench (ISSUE 15): decode p99
under a long-prompt storm, split tiers vs the collapsed baseline.

    python -m k8s_tpu.harness.bench_disagg

The scenario is the production complaint ROADMAP item 2 names: steady
short decodes share a serving fleet with bursts of long prompts, and
every long admission's chunked prefill runs INSIDE the engine loop —
decode-ready slots stall behind it (the convoy
``serve_prefill_convoy_total`` counts), so prefill load directly
convoys decode p99.  Disaggregation splits the fleet into a prefill
tier (chunk-prefill, first token, export the block chain — no decode
slot held) and a decode tier (graft imported chains — no model forward
per migrated request), with the router phase-splitting traffic by
prompt length and the KV block-transfer plane (models/kvxfer.py)
carrying the chains between REAL engines over real sockets.

Both arms run the same three-pod hardware budget (the genjob
--disagg default topology: 1 prefill + 2 decode pods, vs 3 collapsed
pods), each pod a REAL OS process pinned to its own third of the
box's cores, the same tiny CPU model (bench_serve.build_model —
param-bound like real serving), the same router, and the same
workload phases:

- ``unloaded``: short decode clients only;
- ``storm1x``: shorts + N long-prompt clients;
- ``storm2x``: shorts + 2N long-prompt clients (prefill offered load
  doubled).

Embedded assertions (the bench_churn.json artifact contract — a
violation attaches ``failures`` and the artifact still lands):

- **decode p99 stays flat on the split topology**: disaggregated
  shorts' p99 at storm2x <= ``flat_factor`` (1.25) x its own unloaded
  p99 — the prefill tier absorbs the storm, the decode tier never runs
  a prefill longer than one short prompt;
- **the collapsed baseline convoys**: collapsed shorts' p99 at
  storm2x >= ``convoy_factor`` (2.0) x its unloaded p99, with
  ``serve_prefill_convoy_total`` > 0 on its pods — the bench proves
  the disease before claiming the cure;
- **fixed-seed identity**: a long (prompt, seed) answered through the
  disaggregated router (prefill → migrate → decode on another engine)
  is token-identical to a local single-engine call, greedy AND
  sampled — migration moves bytes and the PRNG carry, never the math;
- **migration really happened**: blocks/s migrated > 0 in the storm
  phases, with the per-token transfer overhead
  (``serve_kv_migrate_seconds`` sum / migrated tokens emitted)
  reported in the artifact.

CPU-provable; wired into the non-gating bench_smoke tier as
``bench_operator --disagg`` (artifact ``bench_disagg.json``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request

log = logging.getLogger(__name__)

from k8s_tpu.util.util import quantile_nearest as _quantile  # noqa: E402

DEFAULT_FLAT_FACTOR = 1.25
DEFAULT_CONVOY_FACTOR = 2.0


def _short_prompt(rank: int, i: int, n: int = 8) -> list[int]:
    return [(rank * 17 + i * 13 + j * 5 + 1) % 256 for j in range(n)]


def _long_prompt(rank: int, i: int, n: int) -> list[int]:
    return [(rank * 41 + i * 97 + j * 7 + 11) % 256 for j in range(n)]


def _post(port: int, body: dict, timeout: float = 120.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class _Fleet:
    """One measured topology: three serving pods, each a REAL OS
    process pinned to its own CPU share, behind the real router.
    ``disagg=True`` makes pod 0 the prefill tier and pods 1-2 the
    decode tier with the phase split at ``phase_tokens``; otherwise
    every pod is a collapsed single-role server."""

    def __init__(self, *, disagg: bool, slots: int, phase_tokens: int,
                 hidden: int, layers: int, block_size: int):
        from k8s_tpu import router as router_mod

        self.disagg = disagg
        # the genjob --disagg default topology: ONE prefill pod feeding
        # TWO decode pods (prefill is compute-dense and batch-friendly;
        # decode is where the latency SLO lives), vs three collapsed
        # pods on the identical hardware budget
        roles = ("prefill", "decode", "decode") if disagg \
            else ("", "", "")
        # split the box's cores between the pods: the whole point of
        # disaggregation is that the prefill tier's compute is NOT the
        # decode tier's — an in-process fleet would share one XLA CPU
        # thread pool and prefill would steal decode's cores in BOTH
        # arms, erasing the effect this bench measures.  The collapsed
        # baseline gets the identical split, so the hardware budget is
        # the same in both arms.
        cpus = sorted(os.sched_getaffinity(0)) \
            if hasattr(os, "sched_getaffinity") else []
        share = len(cpus) // len(roles)
        cpu_sets = [cpus[i * share:(i + 1) * share] if share >= 1
                    else None for i in range(len(roles))]
        self.pods = [
            _SubprocPod(role=roles[i], cpus=cpu_sets[i], slots=slots,
                        hidden=hidden, layers=layers)
            for i in range(len(roles))]
        self.ports = [p.port for p in self.pods]
        targets = []
        for i, role in enumerate(roles):
            if role == "prefill":
                targets.append((f"pod-prefill-{i}",
                                f"http://127.0.0.1:{self.ports[i]}",
                                "prefill", None))
            elif role == "decode":
                targets.append((
                    f"pod-decode-{i}",
                    f"http://127.0.0.1:{self.ports[i]}", "decode",
                    f"127.0.0.1:{self.pods[i].kvxfer_port}"))
            else:
                targets.append((f"pod-{i}",
                                f"http://127.0.0.1:{self.ports[i]}"))
        # fingerprint at the ENGINE's block size (read back from the
        # pod — the affinity contract)
        engine_block = int(self.serving_info(0).get("block_size")
                           or block_size)
        self.router = router_mod.Router(
            lambda: targets, block_size=engine_block,
            phase_split_tokens=phase_tokens if disagg else None,
            request_timeout_s=120.0, refresh_interval_s=0.5)
        self.server = router_mod.RouterServer(self.router)
        self.server.start()
        self.port = self.server.port

    def metric_value(self, pod: int, family: str, suffix: str = ""
                     ) -> float:
        """One un-labeled sample value off pod ``pod``'s own /metrics
        (the fleet parser — the same substrate production scrapes)."""
        from k8s_tpu.fleet import parser

        name = family + suffix
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.ports[pod]}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        for fam in parser.parse_exposition(text).values():
            for sname, labels, value in fam.samples:
                if sname == name and not labels:
                    return float(value)
        return 0.0

    def serving_info(self, pod: int) -> dict:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.ports[pod]}/healthz",
                timeout=30) as resp:
            return json.loads(resp.read())["serving"]

    def decode_pods(self) -> list[int]:
        """Indices of the pods that can seat migrations (decode-role on
        the split topology; nobody on the collapsed one)."""
        return [i for i, p in enumerate(self.pods)
                if p.kvxfer_port is not None]

    def blocks_migrated(self) -> float:
        return sum(self.metric_value(i, "serve_kv_blocks_migrated_total")
                   for i in self.decode_pods())

    def kv_imports(self) -> int:
        return int(sum(int(self.serving_info(i).get("kv_imports") or 0)
                       for i in self.decode_pods()))

    def convoys(self) -> int:
        return int(sum(self.metric_value(i, "serve_prefill_convoy_total")
                       for i in range(len(self.pods))))

    def stop(self) -> None:
        self.server.stop()
        for p in self.pods:
            p.stop()


class _SubprocPod:
    """One serving pod as a REAL OS process (``bench_disagg --pod``),
    optionally pinned to a CPU set: builds the same seed-deterministic
    tiny model, runs LmServer + the HTTP listener, prints its ports,
    and serves until killed."""

    def __init__(self, *, role: str, cpus, slots: int, hidden: int,
                 layers: int, timeout: float = 300.0):
        import subprocess
        import sys

        cmd = [sys.executable, "-m", "k8s_tpu.harness.bench_disagg",
               "--pod", "--slots", str(slots), "--hidden", str(hidden),
               "--layers", str(layers)]
        if role:
            cmd += ["--role", role]
        if cpus:
            cmd += ["--cpus", ",".join(str(c) for c in cpus)]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)
        self.port = None
        self.kvxfer_port = None
        deadline = time.monotonic() + timeout
        head: list[str] = []
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"disagg pod (role={role!r}) died during bring-up:\n"
                    + "".join(head[-30:]))
            head.append(line)
            if line.startswith(POD_READY):
                info = json.loads(line[len(POD_READY):])
                self.port = info["port"]
                self.kvxfer_port = info["kvxfer_port"]
                break
        else:
            self.proc.kill()
            raise RuntimeError(
                f"disagg pod (role={role!r}) never became ready:\n"
                + "".join(head[-30:]))
        # drain the pipe so the child can never block on a full buffer
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self) -> None:
        for _line in self.proc.stdout:
            pass

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=30)
        except Exception:  # noqa: BLE001  # except-ok: best-effort teardown of a KILLed pod
            pass


POD_READY = "DISAGG_POD "


def _pod_main(args) -> int:
    """``--pod`` mode: one serving pod process.  CPU affinity is
    applied BEFORE jax imports so the XLA thread pool sizes to the
    pod's share of the box, not the whole box."""
    if args.cpus and hasattr(os, "sched_setaffinity"):
        os.sched_setaffinity(0, {int(c) for c in args.cpus.split(",")})
    from k8s_tpu.harness.bench_serve import build_model
    from k8s_tpu.models import server as server_mod
    from k8s_tpu.util import metrics as metrics_mod

    config, params = build_model(0, hidden=args.hidden,
                                 layers=args.layers)
    lm = server_mod.LmServer(
        config=config, params=params, slots=args.slots,
        queue_limit=256, role=args.role or "",
        kvxfer_port=0 if args.role == "decode" else None,
        registry=metrics_mod.Registry())
    httpd = server_mod.serve(lm)
    print(POD_READY + json.dumps({
        "port": httpd.server_address[1],
        "kvxfer_port": lm._kv_receiver.port
        if lm._kv_receiver is not None else None,
        "role": args.role or "",
    }), flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        lm.close()
    return 0


def _closed_loop_phase(fleet: _Fleet, *, shorts: int, longs: int,
                       duration_s: float, max_new_short: int,
                       max_new_long: int, long_len: int,
                       phase_tag: int) -> dict:
    """One measured phase: ``shorts`` closed-loop short-decode clients
    (their latencies are THE metric) plus ``longs`` closed-loop
    long-prompt clients (the offered prefill load), all through the
    router, for ``duration_s``."""
    lock = threading.Lock()
    short_lat: list[float] = []
    long_lat: list[float] = []
    long_done = [0]
    errors: list[str] = []
    stop = threading.Event()
    barrier = threading.Barrier(shorts + longs + 1)

    def client(rank: int, is_long: bool) -> None:
        barrier.wait()
        time.sleep((rank % 7) * 0.003)  # desynchronize (bench_serve)
        i = 0
        while not stop.is_set():
            if is_long:
                body = {"tokens": _long_prompt(rank, i + phase_tag * 1000,
                                               long_len),
                        "max_new_tokens": max_new_long}
            else:
                body = {"tokens": _short_prompt(rank, i),
                        "max_new_tokens": max_new_short}
            t0 = time.monotonic()
            try:
                out = _post(fleet.port, body)
                if "tokens" not in out:
                    raise RuntimeError(f"bad response: {out}")
            except Exception as e:  # noqa: BLE001 - count, don't crash
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                i += 1
                continue
            dt = time.monotonic() - t0
            with lock:
                if is_long:
                    long_lat.append(dt)
                    long_done[0] += 1
                else:
                    short_lat.append(dt)
            i += 1

    threads = [threading.Thread(target=client, args=(r, False),
                                daemon=True) for r in range(shorts)]
    threads += [threading.Thread(target=client, args=(100 + r, True),
                                 daemon=True) for r in range(longs)]
    for t in threads:
        t.start()
    barrier.wait()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=120)
    short_lat.sort()
    long_lat.sort()
    return {
        "shorts": shorts,
        "longs": longs,
        "duration_s": duration_s,
        "short_requests": len(short_lat),
        "long_requests": long_done[0],
        "errors": errors[:5],
        "error_count": len(errors),
        "short_p50_s": round(_quantile(short_lat, 0.50), 4)
        if short_lat else None,
        "short_p99_s": round(_quantile(short_lat, 0.99), 4)
        if short_lat else None,
        "long_p50_s": round(_quantile(long_lat, 0.50), 4)
        if long_lat else None,
    }


def _run_arm(*, disagg: bool, slots: int, phase_tokens: int,
             shorts: int, longs: int, duration_s: float,
             max_new_short: int, max_new_long: int, long_len: int,
             hidden: int, layers: int,
             identity_probes: list | None = None) -> dict:
    fleet = _Fleet(disagg=disagg, slots=slots,
                   phase_tokens=phase_tokens, hidden=hidden,
                   layers=layers, block_size=16)
    try:
        # warm every program DIRECTLY on the pods that will run it, so
        # no phase pays a compile: EVERY prefill bucket (prefix-reuse
        # CoW tails decompose into arbitrary bucket chains — a shared
        # prefix mid-storm would otherwise compile bucket 1/2/4
        # programs and bill seconds to an unlucky request), the short
        # and long shapes, plus one full migration to warm gather/graft
        # on the split topology
        blen = 1
        buckets = []
        while blen < long_len:
            buckets.append(blen)
            blen *= 2
        for port in fleet.ports:
            for blen in buckets:
                _post(port, {"tokens": _short_prompt(901, blen, blen),
                             "max_new_tokens": 1})
            _post(port, {"tokens": _short_prompt(900, 0),
                         "max_new_tokens": max_new_short})
        if disagg:
            for i in fleet.decode_pods():
                kv = f"127.0.0.1:{fleet.pods[i].kvxfer_port}"
                _post(fleet.ports[0],
                      {"tokens": _long_prompt(900, i, long_len),
                       "max_new_tokens": max_new_long, "kv_dest": kv})
        else:
            for port in fleet.ports:
                _post(port, {"tokens": _long_prompt(900, 0, long_len),
                             "max_new_tokens": max_new_long})
        identity = None
        if identity_probes:
            # fixed-seed identity THROUGH the full hop (router phase
            # split → prefill engine → socket migration → decode
            # engine) vs the parent-side local reference
            identity = {}
            for lane, body, expected in identity_probes:
                routed = _post(fleet.port, body)["tokens"]
                identity[lane] = {"ok": routed == expected,
                                  "local": expected, "routed": routed}
            identity["migrations"] = fleet.kv_imports()
        # unrecorded settle pass: the first seconds after server/router
        # bring-up carry one-time costs (thread-pool spin-up, first-use
        # allocator growth) that would land as phantom outliers in the
        # unloaded baseline's p99 — the ratio assertions compare steady
        # states, not cold starts
        _closed_loop_phase(fleet, shorts=shorts, longs=0,
                           duration_s=min(2.5, duration_s),
                           max_new_short=max_new_short,
                           max_new_long=max_new_long,
                           long_len=long_len, phase_tag=9)
        phases = {}
        for tag, (name, n_long) in enumerate((
                ("unloaded", 0), ("storm1x", longs),
                ("storm2x", 2 * longs))):
            blocks_before = fleet.blocks_migrated() if disagg else 0.0
            t0 = time.monotonic()
            phases[name] = _closed_loop_phase(
                fleet, shorts=shorts, longs=n_long,
                duration_s=duration_s, max_new_short=max_new_short,
                max_new_long=max_new_long, long_len=long_len,
                phase_tag=tag)
            wall = time.monotonic() - t0
            if disagg:
                migrated = fleet.blocks_migrated() - blocks_before
                phases[name]["blocks_migrated"] = int(migrated)
                phases[name]["blocks_per_s_migrated"] = round(
                    migrated / wall, 1)
        out = {
            "topology": "disaggregated" if disagg else "collapsed",
            "phases": phases,
            "prefill_convoys_total": fleet.convoys(),
        }
        if identity is not None:
            out["identity"] = identity
        if disagg:
            # per-token transfer overhead: total sender-side migration
            # seconds (send -> seated ack) over the tokens migrated
            # requests emitted on the decode tier
            mig_sum = fleet.metric_value(0, "serve_kv_migrate_seconds",
                                         "_sum")
            mig_count = fleet.metric_value(0, "serve_kv_migrate_seconds",
                                           "_count")
            long_tokens = sum(
                p["long_requests"] for p in phases.values()) \
                * max_new_long
            out["migrations"] = int(mig_count)
            out["migrate_seconds_total"] = round(mig_sum, 4)
            out["migrate_s_per_migration"] = round(
                mig_sum / mig_count, 5) if mig_count else None
            out["transfer_overhead_s_per_token"] = round(
                mig_sum / long_tokens, 6) if long_tokens else None
            out["kv_exports"] = \
                int(fleet.serving_info(0).get("kv_exports") or 0)
            out["kv_imports"] = fleet.kv_imports()
        return out
    finally:
        fleet.stop()


def _reference_outputs(long_len: int, max_new: int, hidden: int,
                       layers: int) -> list:
    """The parent-side local oracle: greedy + sampled outputs for the
    identity probe prompt from ONE local engine (the engine's own
    batching-invariance tests make this the canonical local answer).
    The engine is torn down before any pod spawns, so its compiles
    never share the box with a measured phase."""
    import numpy as np

    from k8s_tpu.harness.bench_serve import build_model
    from k8s_tpu.models.engine import Engine

    config, params = build_model(0, hidden=hidden, layers=layers)
    engine = Engine(config, params, slots=2, queue_limit=16)
    try:
        probes = []
        prompt = _long_prompt(7, 7, long_len)
        for lane, extra in (("greedy", {}),
                            ("sampled", {"temperature": 1.0, "top_k": 7,
                                         "seed": 1234})):
            local = [int(t) for t in engine.submit(
                np.asarray(prompt, np.int32), max_new,
                temperature=float(extra.get("temperature", 0.0)),
                top_k=extra.get("top_k"),
                seed=int(extra.get("seed", 0)))]
            probes.append((lane,
                           {"tokens": prompt, "max_new_tokens": max_new,
                            **extra},
                           local))
        return probes
    finally:
        engine.shutdown()


def run_bench(shorts: int = 4, longs: int = 3, slots: int = 12,
              duration_s: float = 4.0, max_new_short: int = 17,
              max_new_long: int = 5, long_len: int = 112,
              phase_tokens: int = 48, hidden: int = 256,
              layers: int = 4,
              flat_factor: float = DEFAULT_FLAT_FACTOR,
              convoy_factor: float = DEFAULT_CONVOY_FACTOR) -> dict:
    failures: list[str] = []
    probes = _reference_outputs(long_len, 12, hidden, layers)

    arms = {}
    # the disaggregated arm runs FIRST: whichever arm runs first also
    # absorbs the parent process's one-time costs (client threads,
    # router code paths) as a fatter unloaded tail, which INFLATES its
    # baseline and dilutes its storm ratio — that bias is conservative
    # for the flatness assertion and must not dilute the collapsed
    # arm's convoy ratio
    for disagg in (True, False):
        arms["disaggregated" if disagg else "collapsed"] = _run_arm(
            disagg=disagg, slots=slots,
            phase_tokens=phase_tokens, shorts=shorts, longs=longs,
            duration_s=duration_s, max_new_short=max_new_short,
            max_new_long=max_new_long, long_len=long_len,
            hidden=hidden, layers=layers,
            identity_probes=probes if disagg else None)

    identity = arms["disaggregated"].pop("identity")
    for lane in ("greedy", "sampled"):
        if not identity[lane]["ok"]:
            failures.append(
                f"fixed-seed {lane} output through the disaggregated "
                f"hop differs from local: migration changed the math "
                f"(local {identity[lane]['local'][:6]}... vs routed "
                f"{identity[lane]['routed'][:6]}...)")
    if identity["migrations"] < 1:
        failures.append(
            "identity probes never migrated: the phase split did not "
            "route through the prefill tier")

    dis, col = arms["disaggregated"], arms["collapsed"]
    for name, arm in arms.items():
        errs = sum(p["error_count"] for p in arm["phases"].values())
        if errs:
            failures.append(
                f"{name} arm: {errs} request error(s) "
                f"(first: {next(p['errors'] for p in arm['phases'].values() if p['errors'])})")

    def _ratio(arm) -> tuple:
        base = arm["phases"]["unloaded"]["short_p99_s"]
        stormed = arm["phases"]["storm2x"]["short_p99_s"]
        if not base or not stormed:
            return None, base, stormed
        return stormed / base, base, stormed

    dis_ratio, dis_base, dis_storm = _ratio(dis)
    col_ratio, col_base, col_storm = _ratio(col)
    if dis_ratio is None or col_ratio is None:
        failures.append("a phase produced no short-request latencies")
    else:
        if dis_ratio > flat_factor:
            failures.append(
                f"disaggregated decode p99 degraded {dis_ratio:.2f}x "
                f"({dis_base}s -> {dis_storm}s) under a doubled prefill "
                f"storm (bound {flat_factor}x): the prefill tier is not "
                "absorbing the storm")
        if col_ratio < convoy_factor:
            failures.append(
                f"collapsed decode p99 only degraded {col_ratio:.2f}x "
                f"({col_base}s -> {col_storm}s) under the storm (expected "
                f">= {convoy_factor}x): the workload no longer convoys, "
                "so this bench proves nothing — retune it")
    if col["prefill_convoys_total"] < 1:
        failures.append(
            "collapsed arm recorded zero prefill convoys: the storm "
            "never actually stalled a decode-ready slot")
    storm_blocks = sum(
        dis["phases"][p].get("blocks_migrated", 0)
        for p in ("storm1x", "storm2x"))
    if storm_blocks < 1:
        failures.append(
            "no KV blocks migrated during the storm phases: the "
            "disaggregated arm never exercised the transfer plane")

    result = {
        "metric": "disagg_decode_p99_ratio_under_2x_prefill",
        "value": round(dis_ratio, 3) if dis_ratio else None,
        "unit": "x_vs_unloaded",
        "collapsed_ratio": round(col_ratio, 3) if col_ratio else None,
        "flat_factor_bound": flat_factor,
        "convoy_factor_bound": convoy_factor,
        "model": {"hidden": hidden, "layers": layers},
        "workload": {"shorts": shorts, "longs": longs,
                     "long_len": long_len, "phase_tokens": phase_tokens,
                     "max_new_short": max_new_short,
                     "max_new_long": max_new_long,
                     "duration_s": duration_s, "slots": slots},
        "identity": {
            "greedy_ok": identity["greedy"]["ok"],
            "sampled_ok": identity["sampled"]["ok"],
            "migrations": identity["migrations"],
        },
        "collapsed": col,
        "disaggregated": dis,
    }
    if failures:
        result["failures"] = failures
        err = RuntimeError("disagg bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shorts", type=int, default=4)
    p.add_argument("--longs", type=int, default=3)
    p.add_argument("--slots", type=int, default=12)
    p.add_argument("--duration", type=float, default=4.0)
    p.add_argument("--long-len", type=int, default=112)
    p.add_argument("--phase-tokens", type=int, default=48)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--out", default=None)
    # --pod mode: run as ONE serving pod process (spawned by _Fleet)
    p.add_argument("--pod", action="store_true",
                   help="internal: run as one serving pod process")
    p.add_argument("--role", default="",
                   choices=("", "prefill", "decode"))
    p.add_argument("--cpus", default="",
                   help="internal: comma-separated CPU affinity set")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    if args.pod:
        return _pod_main(args)

    def _write(payload: dict) -> None:
        line = json.dumps(payload)
        print(line)
        if args.out:
            import os

            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                f.write(line + "\n")

    try:
        result = run_bench(
            shorts=args.shorts, longs=args.longs, slots=args.slots,
            duration_s=args.duration, long_len=args.long_len,
            phase_tokens=args.phase_tokens, hidden=args.hidden,
            layers=args.layers)
    except RuntimeError as e:
        partial = getattr(e, "result", None)
        if partial is not None:
            _write(partial)
        raise
    _write(result)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

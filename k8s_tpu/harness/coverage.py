"""Line coverage via sys.monitoring (PEP 669) — no third-party deps.

The reference gated CI on coveralls (.travis.yml:23-33: goveralls over
every package).  This image has no coverage.py, so the harness brings its
own collector: Python 3.12's ``sys.monitoring`` delivers a LINE event per
newly-executed location, and returning ``DISABLE`` from the callback turns
that location off after its FIRST hit — steady-state overhead near zero
(the same mechanism coverage.py 7.4+ uses).

Denominator: executable lines discovered by compiling every source file
under the measured package and walking the code-object tree's
``co_lines()`` — i.e. exactly the lines the interpreter could report.

CLI (the CI ``coverage`` tier):

    python -m k8s_tpu.harness.coverage run --baseline coverage_baseline.json \
        -- -m pytest tests/test_api_defaults.py ...

exits nonzero when measured coverage regresses below the recorded baseline
(minus a small tolerance), and prints the per-run percentage so the tier
log always carries the number.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

TOOL_ID = 3  # a free slot (sys.monitoring reserves 0-5 for tools)


class Collector:
    """First-hit line collector for files under ``root``."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root) + os.sep
        self.hits: dict[str, set[int]] = {}

    def _on_line(self, code, lineno):
        fn = code.co_filename
        if fn.startswith(self.root):
            self.hits.setdefault(fn, set()).add(lineno)
        return sys.monitoring.DISABLE

    def start(self) -> None:
        mon = sys.monitoring
        # prefer the canonical slot, but fall back to any free one: under
        # the full-ladder tier the subprocess shim (sitecustomize.py) may
        # already hold a slot in this interpreter
        self._tool_id = None
        for tool_id in (TOOL_ID, 1, 2, 4, 5):
            try:
                mon.use_tool_id(tool_id, "k8s-tpu-coverage")
            except ValueError:
                continue
            self._tool_id = tool_id
            break
        if self._tool_id is None:
            raise RuntimeError("no free sys.monitoring tool slot")
        mon.register_callback(self._tool_id, mon.events.LINE, self._on_line)
        mon.set_events(self._tool_id, mon.events.LINE)

    def stop(self) -> None:
        mon = sys.monitoring
        mon.set_events(self._tool_id, 0)
        mon.register_callback(self._tool_id, mon.events.LINE, None)
        mon.free_tool_id(self._tool_id)


def executable_lines(path: str) -> set[int]:
    """All line numbers the compiled module could report (co_lines over the
    whole nested code-object tree)."""
    with open(path, "rb") as f:
        src = f.read()
    try:
        top = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [top]
    while stack:
        code = stack.pop()
        for _start, _end, lineno in code.co_lines():
            # line 0 / None are synthetic (module RESUME etc.), never
            # reported by the LINE event
            if lineno:
                lines.add(lineno)
        for const in code.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def iter_sources(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def merge_subprocess_hits(collector: Collector, cov_dir: str) -> int:
    """Union child dumps (written by the repo-root sitecustomize shim) into
    the collector; returns how many child processes contributed."""
    import glob

    n = 0
    for path in glob.glob(os.path.join(cov_dir, "*.json")):
        try:
            with open(path) as f:
                dump = json.load(f)
        except (OSError, ValueError):
            continue  # a child died mid-write: lose that child, not the run
        n += 1
        for fn, lines in dump.items():
            collector.hits.setdefault(fn, set()).update(lines)
    return n


def report(collector: Collector, root: str,
           exclude: tuple[str, ...] = ()) -> dict:
    """``exclude``: package-relative directory prefixes dropped from BOTH
    the numerator and the denominator — a gate scoped to the subsystems
    its test set actually drives (e.g. the control-plane tier excluding
    models/ops/parallel, which the workload tier owns) is not diluted
    every time an unrelated subsystem gains well-tested code."""
    root = os.path.abspath(root)
    skip = tuple(os.path.join(root, e.strip(os.sep)) + os.sep
                 for e in exclude if e)
    files = {}
    total_exec = total_hit = 0
    for path in sorted(iter_sources(root)):
        if skip and path.startswith(skip):
            continue
        execs = executable_lines(path)
        if not execs:
            continue
        hit = collector.hits.get(path, set()) & execs
        total_exec += len(execs)
        total_hit += len(hit)
        files[os.path.relpath(path, os.path.dirname(root))] = {
            "executable": len(execs),
            "hit": len(hit),
            "pct": round(100.0 * len(hit) / len(execs), 1),
        }
    # per-package rollup (first path segment under the measured root):
    # regressions in the tier log are attributable to a subsystem, not
    # just a global percentage (goveralls listed every package)
    packages: dict[str, dict] = {}
    for rel, stats in files.items():
        parts = rel.split(os.sep)
        pkg = parts[1] if len(parts) > 2 else "."
        agg = packages.setdefault(pkg, {"executable": 0, "hit": 0})
        agg["executable"] += stats["executable"]
        agg["hit"] += stats["hit"]
    for agg in packages.values():
        agg["pct"] = round(100.0 * agg["hit"] / max(agg["executable"], 1), 1)
    return {
        "pct": round(100.0 * total_hit / max(total_exec, 1), 2),
        "lines_executable": total_exec,
        "lines_hit": total_hit,
        "files": files,
        "packages": packages,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="measure a python invocation")
    runp.add_argument("--package", default="k8s_tpu",
                      help="source tree to measure (default: k8s_tpu)")
    runp.add_argument("--exclude", default="",
                      help="comma-separated package-relative dirs dropped "
                      "from numerator AND denominator (scope the gate to "
                      "what its test set drives)")
    runp.add_argument("--out", default="",
                      help="write the full JSON report here")
    runp.add_argument("--baseline", default="",
                      help="baseline JSON ({'pct': N}); exit 5 when the "
                      "measured pct drops more than --tolerance below it")
    runp.add_argument("--tolerance", type=float, default=1.0,
                      help="allowed regression in percentage points")
    runp.add_argument("--update-baseline", action="store_true",
                      help="rewrite the baseline file with this run's pct")
    runp.add_argument("--no-subprocess", action="store_true",
                      help="skip the sitecustomize subprocess collector "
                      "(in-process lines only)")
    runp.add_argument("argv", nargs=argparse.REMAINDER,
                      help="-- -m pytest ... (a python command line)")
    args = p.parse_args(argv)

    cmd = list(args.argv)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("give the python command after --, e.g. -- -m pytest tests -q")

    repo = os.getcwd()
    package_root = os.path.join(repo, args.package)
    collector = Collector(package_root)

    # Subprocess collection: the repo-root sitecustomize shim starts a
    # child collector in every python subprocess that sees these env vars
    # (operator binaries, gang workers, kubelet pods) and dumps hits for
    # the merge below.  Repo root is prepended to PYTHONPATH so even
    # children spawned with a bare inherited environment import the shim.
    import tempfile

    saved_env = {k: os.environ.get(k) for k in
                 ("K8S_TPU_COV_DIR", "K8S_TPU_COV_ROOT", "PYTHONPATH")}
    cov_dir = None
    if not args.no_subprocess:
        cov_dir = tempfile.mkdtemp(prefix="k8s-tpu-cov-")
        os.environ["K8S_TPU_COV_DIR"] = cov_dir
        os.environ["K8S_TPU_COV_ROOT"] = package_root
        os.environ["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo, saved_env["PYTHONPATH"]) if p)
    collector.start()
    try:
        rc = _run_python_argv(cmd)
    finally:
        collector.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    children = 0
    if cov_dir:
        children = merge_subprocess_hits(collector, cov_dir)
        import shutil

        shutil.rmtree(cov_dir, ignore_errors=True)

    exclude = tuple(e.strip() for e in args.exclude.split(",") if e.strip())
    rep = report(collector, package_root, exclude=exclude)
    scope = (f"{args.package} minus {','.join(exclude)}" if exclude
             else args.package)
    print(f"coverage: {rep['pct']}% "
          f"({rep['lines_hit']}/{rep['lines_executable']} lines of "
          f"{scope}; {children} subprocess(es) merged)")
    width = max((len(p) for p in rep["packages"]), default=1)
    for pkg in sorted(rep["packages"]):
        agg = rep["packages"][pkg]
        print(f"coverage:   {pkg:<{width}} {agg['pct']:>5.1f}% "
              f"({agg['hit']}/{agg['executable']})")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.baseline:
        if args.update_baseline or not os.path.exists(args.baseline):
            with open(args.baseline, "w") as f:
                json.dump({"pct": rep["pct"]}, f)
                f.write("\n")
            print(f"coverage: baseline written: {rep['pct']}%")
        else:
            with open(args.baseline) as f:
                base = json.load(f)["pct"]
            if rep["pct"] < base - args.tolerance:
                print(
                    f"coverage: REGRESSION: {rep['pct']}% < baseline "
                    f"{base}% - {args.tolerance}",
                    file=sys.stderr,
                )
                return 5
            print(f"coverage: ok vs baseline {base}% "
                  f"(tolerance {args.tolerance})")
    return rc


def _run_python_argv(cmd: list[str]) -> int:
    """Execute ``-m module args...`` or ``script.py args...`` in-process so
    the monitoring tool observes it."""
    import runpy

    if cmd[0] == "-m":
        module, rest = cmd[1], cmd[2:]
        old_argv = sys.argv
        sys.argv = [module] + rest
        try:
            if module == "pytest":
                import pytest

                return pytest.main(rest)
            runpy.run_module(module, run_name="__main__")
            return 0
        except SystemExit as e:
            return int(e.code or 0)
        finally:
            sys.argv = old_argv
    old_argv = sys.argv
    sys.argv = cmd
    try:
        runpy.run_path(cmd[0], run_name="__main__")
        return 0
    except SystemExit as e:
        return int(e.code or 0)
    finally:
        sys.argv = old_argv


if __name__ == "__main__":
    sys.exit(main())

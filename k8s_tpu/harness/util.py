"""Shared harness utilities (reference: py/util.py:39-504).

The reference's GKE/gcloud helpers are replaced by the local/fake cluster
lifecycle in k8s_tpu.harness.deploy; what remains here is the generic
subprocess/retry/timeout surface the rest of the harness uses.
"""

from __future__ import annotations

import logging
import re
import subprocess
import time

log = logging.getLogger(__name__)


class TimeoutError(Exception):  # noqa: A001 - mirrors py/util.py TimeoutError
    """An operation timed out (py/util.py:504)."""


_URL_USERINFO = re.compile(r"(?<=://)[^/@\s]+@")


def _redact(arg: str) -> str:
    """Strip URL userinfo (user:token@) so credential-bearing clone URLs
    never reach persisted CI logs."""
    return _URL_USERINFO.sub("<redacted>@", arg)


def run(command: list[str], cwd: str | None = None, env: dict | None = None) -> None:
    """Run a command logging it first; raises CalledProcessError on failure
    (py/util.py:39-60)."""
    log.info("Running: %s", " ".join(_redact(c) for c in command))
    try:
        subprocess.check_call(command, cwd=cwd, env=env)
    except subprocess.CalledProcessError as e:
        # e.cmd ends up in tracebacks and persisted junit output; strip
        # credential-bearing URLs (release.py git_clone) there too
        raise _redacted_error(e) from None


def run_and_output(
    command: list[str], cwd: str | None = None, env: dict | None = None
) -> str:
    """Run a command and return its combined output (py/util.py:63-87)."""
    log.info("Running: %s", " ".join(_redact(c) for c in command))
    try:
        return subprocess.check_output(
            command, cwd=cwd, env=env, stderr=subprocess.STDOUT
        ).decode()
    except subprocess.CalledProcessError as e:
        raise _redacted_error(e) from None


def _redacted_error(e: subprocess.CalledProcessError) -> subprocess.CalledProcessError:
    cmd = e.cmd
    if isinstance(cmd, (list, tuple)):
        cmd = [_redact(str(c)) for c in cmd]
    else:
        cmd = _redact(str(cmd))

    def scrub(out):
        # git prints the failing URL to stderr→output; junit wrap_test
        # persists e.output verbatim, so it needs the same redaction
        if out is None:
            return None
        if isinstance(out, bytes):
            return _redact(out.decode(errors="replace")).encode()
        return _redact(out)

    return subprocess.CalledProcessError(
        e.returncode, cmd, scrub(e.output), scrub(e.stderr))


def wait_for(
    predicate,
    timeout_s: float,
    polling_interval_s: float = 1.0,
    description: str = "condition",
):
    """Poll ``predicate`` until it returns a truthy value or the deadline
    passes (the reference's various wait_for_* loops, e.g. py/util.py:189)."""
    deadline = time.monotonic() + timeout_s
    while True:
        result = predicate()
        if result:
            return result
        if time.monotonic() + polling_interval_s > deadline:
            raise TimeoutError(f"Timeout waiting for {description}")
        time.sleep(polling_interval_s)

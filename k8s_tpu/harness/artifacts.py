"""Artifact store abstraction.

The reference writes CI artifacts to GCS (``gs://bucket/path`` URIs threaded
through py/prow.py and py/test_util.py).  In the zero-egress TPU image the
same layout lands on the local filesystem; the store interface keeps the
prow/junit code transport-agnostic so a GCS (or GCS-compatible) store can be
slotted in for real CI.

URIs use ``<scheme>://<bucket>/<path>`` like the reference's
``util.split_gcs_uri`` (py/util.py:447-457); plain paths are treated as
local files.
"""

from __future__ import annotations

import os
import re
from typing import Iterable

_URI_RE = re.compile(r"^([a-z][a-z0-9+.-]*)://([^/]*)/?(.*)$")


def split_uri(uri: str) -> tuple[str, str]:
    """Split ``scheme://bucket/path`` into (bucket, path)
    (py/util.py:447-457 split_gcs_uri)."""
    m = _URI_RE.match(uri)
    if not m:
        raise ValueError(f"not a store URI: {uri!r}")
    return m.group(2), m.group(3)


def is_store_uri(uri: str) -> bool:
    return bool(_URI_RE.match(uri))


class LocalArtifactStore:
    """Filesystem-backed store: bucket → directory under ``root``."""

    def __init__(self, root: str):
        self.root = root

    def _path(self, bucket: str, path: str) -> str:
        return os.path.join(self.root, bucket, path)

    def upload_from_string(self, bucket: str, path: str, data: str) -> str:
        full = self._path(bucket, path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "w") as f:
            f.write(data)
        return full

    def upload_from_filename(self, bucket: str, path: str, filename: str) -> str:
        with open(filename) as f:
            return self.upload_from_string(bucket, path, f.read())

    def download_as_string(self, bucket: str, path: str) -> str:
        with open(self._path(bucket, path)) as f:
            return f.read()

    def exists(self, bucket: str, path: str) -> bool:
        return os.path.exists(self._path(bucket, path))

    def list(self, bucket: str, prefix: str) -> Iterable[str]:
        """Yield object paths (relative to the bucket) under ``prefix``."""
        base = os.path.join(self.root, bucket)
        for dirpath, _dirs, files in os.walk(base):
            for name in files:
                rel = os.path.relpath(os.path.join(dirpath, name), base)
                if rel.startswith(prefix):
                    yield rel

"""Lint + unit-test driver (reference: py/py_checks.py:18-144).

The reference runs pylint over every ``.py`` file and executes ``*_test.py``
files, emitting one junit XML per check.  Here lint is ``pyflakes`` when
importable, else a ``compile()`` syntax pass (no pylint in this image), and
the test tier runs pytest; junit files land in ``--artifacts_dir`` for
:func:`k8s_tpu.harness.prow.check_no_errors` to inspect.

The lint tier additionally runs the static concurrency analyzer
(:mod:`k8s_tpu.analysis`, ISSUE 10) over the whole ``k8s_tpu`` tree —
lock-order cycles, guarded-by discipline, blocking-calls-under-lock — with
its own junit + JSON artifact, and the static compile-surface analyzer
(:mod:`k8s_tpu.analysis.compilesurface`, ISSUE 11) — per-call
``jax.jit`` constructions, uncovered traced branches, host-device syncs
in the engine's hot loop or under a lock, swallowed broad exception
handlers — likewise with junit + JSON artifacts; see
docs/static_analysis.md for the annotation and allowlist syntax.
"""

from __future__ import annotations

import argparse
import logging
import os
import subprocess
import sys
import time

from k8s_tpu.analysis import astutil
from k8s_tpu.harness import junit

log = logging.getLogger(__name__)

EXCLUDE_DIRS = astutil.EXCLUDE_DIRS  # shared with the analysis AST walkers


# Packages that must stay stdlib-only (plus themselves): trace/ rides the
# REST client's request hot path; scheduler/ (ISSUE 4) holds cross-job
# admission state consulted from every sync and is served by two HTTP
# processes; flight/ (ISSUE 7) is the control-plane flight recorder — call
# accounting on the REST request hot path, watch health in the reflector
# loop, lifecycle timelines served by two HTTP processes; fleet/ (ISSUE 8)
# is the fleet telemetry plane — a scrape thread inside the operator
# process, read by two HTTP processes, all informer/TFJob knowledge kept
# with its callers; analysis/ (ISSUE 10) is the concurrency auditor whose
# checkedlock wrappers sit inside every hot-path lock; router/ (ISSUE 13)
# is the serving front door + autoscaler — a standalone proxy process and
# an operator control loop served by three HTTP processes.  None may grow
# a third-party (or out-of-family intra-repo) import — with ONE carve-out:
# any of them may import another STDLIB_ONLY_PACKAGES member (each is
# itself gated, so the transitive stdlib guarantee holds): checkedlock
# factories from ``analysis``, and the router's reuse of ``fleet``
# discovery types + per-pod rollup reads.
STDLIB_ONLY_PACKAGES = ("k8s_tpu.trace", "k8s_tpu.scheduler",
                        "k8s_tpu.flight", "k8s_tpu.fleet",
                        "k8s_tpu.analysis", "k8s_tpu.router")


def check_stdlib_only(path: str, source: bytes | None = None,
                      package: str = "k8s_tpu.trace") -> list[str]:
    """Stdlib-only gate for one of STDLIB_ONLY_PACKAGES: only the standard
    library and the package itself may be imported.

    Returns one message per offending import (empty = clean).
    """
    import ast

    if source is None:
        with open(path, "rb") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []  # the syntax layer reports this one
    violations = []
    pkg_path = package.replace(".", "/")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names = [alias.name for alias in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: stays inside the package
                continue
            names = [node.module or ""]
        else:
            continue
        for name in names:
            if name == package or name.startswith(package + "."):
                continue
            if any(name == member or name.startswith(member + ".")
                   for member in STDLIB_ONLY_PACKAGES):
                # family carve-out (see STDLIB_ONLY_PACKAGES): every
                # member is itself gated, so the guarantee is transitive
                continue
            if name.split(".", 1)[0] in sys.stdlib_module_names:
                continue
            violations.append(
                f"non-stdlib import '{name}' in {pkg_path} "
                f"(stdlib-only package; line {node.lineno})")
    return violations


def check_trace_stdlib(path: str, source: bytes | None = None) -> list[str]:
    """Back-compat alias: the original trace-only gate."""
    return check_stdlib_only(path, source, package="k8s_tpu.trace")


def _stdlib_only_package_of(path: str) -> str | None:
    norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
    for package in STDLIB_ONLY_PACKAGES:
        if f"/{package.replace('.', '/')}/" in norm:
            return package
    return None


iter_py_files = astutil.iter_py_files


def _lint_one(path: str) -> str | None:
    """Return a failure message or None (the per-file pylint run,
    py_checks.py:40-62).

    Three layers: compile() for syntax, the in-tree AST/symtable linter
    (harness.pylint_lite — undefined names, unused imports, mutable
    defaults, bare except, ...), and pyflakes on top when the image has it.
    """
    with open(path, "rb") as f:
        source = f.read()
    try:
        compile(source, path, "exec")
    except SyntaxError as e:
        return f"SyntaxError: {e}"
    stdlib_only_pkg = _stdlib_only_package_of(path)
    if stdlib_only_pkg:
        violations = check_stdlib_only(path, source, package=stdlib_only_pkg)
        if violations:
            return "\n".join(violations)
    from k8s_tpu.harness import pylint_lite

    findings = pylint_lite.check_file(path)
    if findings:
        return "\n".join(str(f) for f in findings)
    try:
        from pyflakes.api import check as pyflakes_check
        from pyflakes.reporter import Reporter
        import io

        out, err = io.StringIO(), io.StringIO()
        if pyflakes_check(source.decode("utf-8", "replace"), path, Reporter(out, err)):
            return (out.getvalue() + err.getvalue()).strip()
    except ImportError:
        pass
    return None


def run_lint(src_dir: str, artifacts_dir: str) -> bool:
    """Lint the tree; junit_pylint.xml analogue (py_checks.py:18-85)."""
    suite = junit.TestSuite("pylint")
    ok = True
    for path in iter_py_files(src_dir):
        case = suite.create(os.path.relpath(path, src_dir))
        start = time.time()
        failure = _lint_one(path)
        case.time = time.time() - start
        if failure:
            case.failure = failure
            ok = False
    junit.create_junit_xml_file(suite, os.path.join(artifacts_dir, "junit_pylint.xml"))
    return ok


def run_concurrency(src_dir: str, artifacts_dir: str) -> bool:
    """The static concurrency analyzer (ISSUE 10) as a lint-tier gate:
    one junit case per check pass, plus the full report JSON artifact
    (``concurrency_report.json``).  Zero unexplained allowlist entries by
    construction — the allowlist loader rejects reason-less lines and
    stale entries become findings."""
    import json

    from k8s_tpu.analysis import static

    suite = junit.TestSuite("concurrency")
    start = time.time()
    tree_root = os.path.join(src_dir, "k8s_tpu")
    if not os.path.isdir(tree_root):
        tree_root = src_dir
    allowlist = os.path.join(tree_root, "analysis", "allowlist.txt")
    case = suite.create("analyze")
    try:
        report = static.analyze_tree(
            tree_root,
            allowlist_path=allowlist if os.path.exists(allowlist) else None,
            rel_base=os.path.dirname(os.path.abspath(tree_root)))
    except static.AllowlistError as e:
        case.failure = f"unexplained allowlist entry: {e}"
        case.time = time.time() - start
        junit.create_junit_xml_file(
            suite, os.path.join(artifacts_dir, "junit_concurrency.xml"))
        return False
    case.time = time.time() - start
    by_code: dict[str, list] = {}
    for f in report.findings:
        by_code.setdefault(f.code, []).append(f)
    for code in ("lock-order-cycle", "guarded-by", "blocking-under-lock",
                 "stale-allowlist"):
        sub = suite.create(code)
        # time-less cases render as "Test was not run." failures in
        # junit.create_xml, and prow.check_no_errors fails the job on any
        sub.time = 0.0
        found = by_code.get(code, [])
        if found:
            sub.failure = "\n".join(str(f) for f in found)
    with open(os.path.join(artifacts_dir, "concurrency_report.json"),
              "w", encoding="utf-8") as f:
        json.dump(report.as_dict(), f, indent=1, sort_keys=True)
    junit.create_junit_xml_file(
        suite, os.path.join(artifacts_dir, "junit_concurrency.xml"))
    if not report.ok:
        for finding in report.findings:
            log.error("concurrency: %s", finding)
    return report.ok


def run_compile_surface(src_dir: str, artifacts_dir: str) -> bool:
    """The static compile-surface analyzer (ISSUE 11) as a lint-tier
    gate — the :func:`run_concurrency` shape: one junit case per check
    pass, plus the full report JSON artifact
    (``compile_surface_report.json``).  Allowlist entries are
    reason-mandatory and stale entries become findings, so nothing is
    exempt without an auditable justification."""
    import json

    from k8s_tpu.analysis import compilesurface

    suite = junit.TestSuite("compile_surface")
    start = time.time()
    tree_root = os.path.join(src_dir, "k8s_tpu")
    if not os.path.isdir(tree_root):
        tree_root = src_dir
    allowlist = os.path.join(tree_root, "analysis", "compile_allowlist.txt")
    case = suite.create("analyze")
    try:
        report = compilesurface.analyze_tree(
            tree_root,
            allowlist_path=allowlist if os.path.exists(allowlist) else None,
            rel_base=os.path.dirname(os.path.abspath(tree_root)))
    except compilesurface.AllowlistError as e:
        case.failure = f"unexplained allowlist entry: {e}"
        case.time = time.time() - start
        junit.create_junit_xml_file(
            suite, os.path.join(artifacts_dir, "junit_compile_surface.xml"))
        return False
    case.time = time.time() - start
    by_code: dict[str, list] = {}
    for f in report.findings:
        by_code.setdefault(f.code, []).append(f)
    for code in ("jit-per-call", "jit-in-loop", "uncovered-traced-branch",
                 "host-sync-hot-loop", "host-sync-under-lock",
                 "swallowed-exception", "stale-allowlist"):
        sub = suite.create(code)
        # time-less cases render as "Test was not run." failures in
        # junit.create_xml, and prow.check_no_errors fails the job on any
        sub.time = 0.0
        found = by_code.get(code, [])
        if found:
            sub.failure = "\n".join(str(f) for f in found)
    with open(os.path.join(artifacts_dir, "compile_surface_report.json"),
              "w", encoding="utf-8") as f:
        json.dump(report.as_dict(), f, indent=1, sort_keys=True)
    junit.create_junit_xml_file(
        suite, os.path.join(artifacts_dir, "junit_compile_surface.xml"))
    if not report.ok:
        for finding in report.findings:
            log.error("compile-surface: %s", finding)
    return report.ok


def run_tests(src_dir: str, artifacts_dir: str) -> bool:
    """Run the pytest tier writing junit_pytests.xml (the *_test.py loop of
    py_checks.py:86-121, delegated to pytest's own junit emitter)."""
    os.makedirs(artifacts_dir, exist_ok=True)
    result = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/",
            "-q",
            f"--junitxml={os.path.join(artifacts_dir, 'junit_pytests.xml')}",
        ],
        cwd=src_dir,
    )
    return result.returncode == 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--src_dir", default=os.getcwd())
    parser.add_argument("--artifacts_dir", required=True)
    parser.add_argument(
        "--check", choices=["lint", "test", "all"], default="all",
        help="which tier to run (py_checks.py runs both)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    os.makedirs(args.artifacts_dir, exist_ok=True)
    ok = True
    if args.check in ("lint", "all"):
        ok = run_lint(args.src_dir, args.artifacts_dir) and ok
        ok = run_concurrency(args.src_dir, args.artifacts_dir) and ok
        ok = run_compile_surface(args.src_dir, args.artifacts_dir) and ok
    if args.check in ("test", "all"):
        ok = run_tests(args.src_dir, args.artifacts_dir) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Lint + unit-test driver (reference: py/py_checks.py:18-144).

The reference runs pylint over every ``.py`` file and executes ``*_test.py``
files, emitting one junit XML per check.  Here lint is ``pyflakes`` when
importable, else a ``compile()`` syntax pass (no pylint in this image), and
the test tier runs pytest; junit files land in ``--artifacts_dir`` for
:func:`k8s_tpu.harness.prow.check_no_errors` to inspect.
"""

from __future__ import annotations

import argparse
import logging
import os
import subprocess
import sys
import time

from k8s_tpu.harness import junit

log = logging.getLogger(__name__)

EXCLUDE_DIRS = {".git", "__pycache__", ".eggs", "build", "vendor", "node_modules"}


# Packages that must stay stdlib-only (plus themselves): trace/ rides the
# REST client's request hot path; scheduler/ (ISSUE 4) holds cross-job
# admission state consulted from every sync and is served by two HTTP
# processes; flight/ (ISSUE 7) is the control-plane flight recorder — call
# accounting on the REST request hot path, watch health in the reflector
# loop, lifecycle timelines served by two HTTP processes; fleet/ (ISSUE 8)
# is the fleet telemetry plane — a scrape thread inside the operator
# process, read by two HTTP processes, all informer/TFJob knowledge kept
# with its callers.  None may grow a third-party (or even intra-repo)
# import.
STDLIB_ONLY_PACKAGES = ("k8s_tpu.trace", "k8s_tpu.scheduler",
                        "k8s_tpu.flight", "k8s_tpu.fleet")


def check_stdlib_only(path: str, source: bytes | None = None,
                      package: str = "k8s_tpu.trace") -> list[str]:
    """Stdlib-only gate for one of STDLIB_ONLY_PACKAGES: only the standard
    library and the package itself may be imported.

    Returns one message per offending import (empty = clean).
    """
    import ast

    if source is None:
        with open(path, "rb") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []  # the syntax layer reports this one
    violations = []
    pkg_path = package.replace(".", "/")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names = [alias.name for alias in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: stays inside the package
                continue
            names = [node.module or ""]
        else:
            continue
        for name in names:
            if name == package or name.startswith(package + "."):
                continue
            if name.split(".", 1)[0] in sys.stdlib_module_names:
                continue
            violations.append(
                f"non-stdlib import '{name}' in {pkg_path} "
                f"(stdlib-only package; line {node.lineno})")
    return violations


def check_trace_stdlib(path: str, source: bytes | None = None) -> list[str]:
    """Back-compat alias: the original trace-only gate."""
    return check_stdlib_only(path, source, package="k8s_tpu.trace")


def _stdlib_only_package_of(path: str) -> str | None:
    norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
    for package in STDLIB_ONLY_PACKAGES:
        if f"/{package.replace('.', '/')}/" in norm:
            return package
    return None


def iter_py_files(src_dir: str):
    for root, dirs, files in os.walk(src_dir):
        dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def _lint_one(path: str) -> str | None:
    """Return a failure message or None (the per-file pylint run,
    py_checks.py:40-62).

    Three layers: compile() for syntax, the in-tree AST/symtable linter
    (harness.pylint_lite — undefined names, unused imports, mutable
    defaults, bare except, ...), and pyflakes on top when the image has it.
    """
    with open(path, "rb") as f:
        source = f.read()
    try:
        compile(source, path, "exec")
    except SyntaxError as e:
        return f"SyntaxError: {e}"
    stdlib_only_pkg = _stdlib_only_package_of(path)
    if stdlib_only_pkg:
        violations = check_stdlib_only(path, source, package=stdlib_only_pkg)
        if violations:
            return "\n".join(violations)
    from k8s_tpu.harness import pylint_lite

    findings = pylint_lite.check_file(path)
    if findings:
        return "\n".join(str(f) for f in findings)
    try:
        from pyflakes.api import check as pyflakes_check
        from pyflakes.reporter import Reporter
        import io

        out, err = io.StringIO(), io.StringIO()
        if pyflakes_check(source.decode("utf-8", "replace"), path, Reporter(out, err)):
            return (out.getvalue() + err.getvalue()).strip()
    except ImportError:
        pass
    return None


def run_lint(src_dir: str, artifacts_dir: str) -> bool:
    """Lint the tree; junit_pylint.xml analogue (py_checks.py:18-85)."""
    suite = junit.TestSuite("pylint")
    ok = True
    for path in iter_py_files(src_dir):
        case = suite.create(os.path.relpath(path, src_dir))
        start = time.time()
        failure = _lint_one(path)
        case.time = time.time() - start
        if failure:
            case.failure = failure
            ok = False
    junit.create_junit_xml_file(suite, os.path.join(artifacts_dir, "junit_pylint.xml"))
    return ok


def run_tests(src_dir: str, artifacts_dir: str) -> bool:
    """Run the pytest tier writing junit_pytests.xml (the *_test.py loop of
    py_checks.py:86-121, delegated to pytest's own junit emitter)."""
    os.makedirs(artifacts_dir, exist_ok=True)
    result = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/",
            "-q",
            f"--junitxml={os.path.join(artifacts_dir, 'junit_pytests.xml')}",
        ],
        cwd=src_dir,
    )
    return result.returncode == 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--src_dir", default=os.getcwd())
    parser.add_argument("--artifacts_dir", required=True)
    parser.add_argument(
        "--check", choices=["lint", "test", "all"], default="all",
        help="which tier to run (py_checks.py runs both)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    os.makedirs(args.artifacts_dir, exist_ok=True)
    ok = True
    if args.check in ("lint", "all"):
        ok = run_lint(args.src_dir, args.artifacts_dir) and ok
    if args.check in ("test", "all"):
        ok = run_tests(args.src_dir, args.artifacts_dir) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""TFJob lifecycle client for the harness (reference: py/tf_job_client.py).

Works on raw dicts against the k8s_tpu clientset (fake or REST backend), the
way the reference drives CustomObjectsApi.  Keeps the version-aware terminal
check: v1alpha1 is finished when ``status.phase == Done``
(tf_job_client.py:146-148), v1alpha2 when ``status.completionTime`` is set
(tf_job_client.py:149-152).
"""

from __future__ import annotations

import datetime
import logging
import time

from k8s_tpu.harness.util import TimeoutError

log = logging.getLogger(__name__)

TF_JOB_GROUP = "kubeflow.org"
TF_JOB_PLURAL = "tfjobs"
TF_JOB_KIND = "TFJob"


def _api_version(version: str) -> str:
    return version if "/" in version else f"{TF_JOB_GROUP}/{version}"


def create_tf_job(clientset, spec: dict, version: str = "v1alpha1") -> dict:
    """Create a TFJob from a raw spec dict (tf_job_client.py:21-56)."""
    namespace = (spec.get("metadata") or {}).get("namespace", "default")
    created = clientset.tfjobs_unstructured(namespace, _api_version(version)).create(
        spec
    )
    log.info("Created job %s", created["metadata"]["name"])
    return created


def delete_tf_job(
    clientset, namespace: str, name: str, version: str = "v1alpha1"
) -> None:
    """Delete with Foreground propagation so the job lingers until owned
    resources are gone (tf_job_client.py:58-92)."""
    log.info("Deleting job %s.%s", namespace, name)
    clientset.tfjobs_unstructured(namespace, _api_version(version)).delete(
        name, propagation="Foreground"
    )


def log_status(tf_job: dict) -> None:
    """Status callback for wait_for_job (tf_job_client.py:96-103)."""
    log.info(
        "Job %s in namespace %s; uid=%s; phase=%s, state=%s",
        (tf_job.get("metadata") or {}).get("name"),
        (tf_job.get("metadata") or {}).get("namespace"),
        (tf_job.get("metadata") or {}).get("uid"),
        (tf_job.get("status") or {}).get("phase"),
        (tf_job.get("status") or {}).get("state"),
    )


def is_job_finished(tf_job: dict, version: str = "v1alpha1") -> bool:
    """Version-aware terminal check (tf_job_client.py:144-152)."""
    status = tf_job.get("status") or {}
    if version.endswith("v1alpha1"):
        return status.get("phase") == "Done"
    return bool(status.get("completionTime"))


def wait_for_job(
    clientset,
    namespace: str,
    name: str,
    version: str = "v1alpha1",
    timeout: datetime.timedelta = datetime.timedelta(minutes=10),
    polling_interval: datetime.timedelta = datetime.timedelta(seconds=30),
    status_callback=None,
) -> dict:
    """Poll until the job reaches its terminal state
    (tf_job_client.py:104-161)."""
    client = clientset.tfjobs_unstructured(namespace, _api_version(version))
    end_time = datetime.datetime.now() + timeout
    while True:
        results = client.get(name)
        if results:
            if status_callback:
                status_callback(results)
            if is_job_finished(results, version):
                return results
        if datetime.datetime.now() + polling_interval > end_time:
            raise TimeoutError(
                f"Timeout waiting for job {name} in namespace {namespace} to finish."
            )
        time.sleep(polling_interval.total_seconds())

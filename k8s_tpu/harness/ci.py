"""CI tier/workflow runner driven by ci_config.yaml.

Reference counterpart: prow_config.yaml:3-6 routed Argo e2e workflows (via
kubeflow/testing's run_e2e_workflow.py) and .travis.yml:23-33 ran the
build/lint/unit tiers.  Here one config file declares both, and this module
is the single entrypoint CI systems call:

    python -m k8s_tpu.harness.ci <tier>        # lint / unit / controller...
    python -m k8s_tpu.harness.ci --workflow tpujob-e2e
    python -m k8s_tpu.harness.ci --all

Each tier's command runs in the repo root; failures propagate as a nonzero
exit code and a junit file per tier lands in ``artifacts.junit_dir`` (the
harness.prow artifact contract).
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import time

import yaml

from k8s_tpu.harness import junit

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_CONFIG = os.path.join(REPO_ROOT, "ci_config.yaml")


def load_config(path: str = DEFAULT_CONFIG) -> dict:
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    # explicit-null sections (`tiers:` with every entry commented out)
    # normalize to empty, not None
    for key, empty in (("tiers", {}), ("workflows", []), ("artifacts", {})):
        if cfg.get(key) is None:
            cfg[key] = empty
    return cfg


def _run_entry(name: str, entry: str, junit_dir: str | None,
               timeout: float | None = None, cwd: str = REPO_ROOT) -> bool:
    """Run one tier/workflow command; write a junit TestCase for it."""
    start = time.time()
    try:
        proc = subprocess.run(
            shlex.split(entry), cwd=cwd, timeout=timeout,
            capture_output=True, text=True,
        )
        ok = proc.returncode == 0
        failure = None if ok else (
            f"exit {proc.returncode}\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
        )
        out_tail = proc.stdout[-4000:] + proc.stderr[-4000:]
    except subprocess.TimeoutExpired as e:
        ok = False
        failure = f"timeout after {timeout:.0f}s"

        def _tail(stream):
            if isinstance(stream, bytes):
                return stream[-4000:].decode(errors="replace")
            return (stream or "")[-4000:]

        out_tail = _tail(e.stdout) + _tail(e.stderr)
    elapsed = time.time() - start
    case = junit.TestCase(class_name="ci", name=name)
    case.time = elapsed
    case.failure = failure
    if junit_dir:
        os.makedirs(junit_dir, exist_ok=True)
        junit.create_junit_xml_file(
            [case], os.path.join(junit_dir, f"junit_ci-{name}.xml"))
    stream = sys.stdout if ok else sys.stderr
    counts = _pytest_counts(out_tail)
    suffix = f"; {counts}" if counts else ""
    print(f"[ci] {name}: {'PASS' if ok else 'FAIL'} "
          f"({elapsed:.1f}s{suffix})", file=stream)
    if not ok:
        print(out_tail, file=sys.stderr)
    return ok


def _pytest_counts(output: str) -> str:
    """Extract "N passed[, M skipped][, ...]" from pytest's SUMMARY line
    (the one ending "in X.XXs") so the ladder log carries per-tier test
    counts — skips (hardware-gated tests) stay VISIBLE instead of silently
    shrinking the round's authoritative total (VERDICT r4 #8).  Anchored to
    the summary line so non-pytest tiers printing "2 errors" elsewhere
    never grow a bogus count suffix."""
    import re

    counts = ""
    for line in output.splitlines():
        if not re.search(r" in [0-9.]+s\b", line):
            continue
        matches = re.findall(
            r"\d+ (?:passed|skipped|failed|errors?|xfailed|xpassed"
            r"|deselected)\b", line)
        if matches:
            counts = ", ".join(matches)
    return counts


def run_tier(cfg: dict, name: str) -> bool:
    tier = cfg["tiers"].get(name)
    if tier is None:
        raise KeyError(f"unknown tier {name!r}; have {sorted(cfg['tiers'])}")
    entry = tier["entry"] if isinstance(tier, dict) else str(tier)
    gating = tier.get("gating", True) if isinstance(tier, dict) else True
    ok = _run_entry(name, entry, cfg["artifacts"].get("junit_dir"))
    if not ok and not gating:
        # Non-gating tiers (perf smoke benches) report + record junit but
        # never fail the ladder: their numbers are advisory trend data.
        print(f"[ci] {name}: failure ignored (gating: false)",
              file=sys.stderr)
        return True
    return ok


def run_workflow(cfg: dict, name: str) -> bool:
    for wf in cfg["workflows"]:
        if wf.get("name") == name:
            timeout = 60.0 * float(wf.get("timeout_minutes", 30))
            return _run_entry(name, wf["entry"],
                              cfg["artifacts"].get("junit_dir"), timeout)
    raise KeyError(
        f"unknown workflow {name!r}; have {[w.get('name') for w in cfg['workflows']]}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("tier", nargs="?", help="tier name from ci_config.yaml")
    p.add_argument("--workflow", help="workflow name from ci_config.yaml")
    p.add_argument("--all", action="store_true", help="run every tier in order")
    p.add_argument("--config", default=DEFAULT_CONFIG)
    args = p.parse_args(argv)

    cfg = load_config(args.config)
    if args.all:
        ok = all([run_tier(cfg, t) for t in cfg["tiers"]])
    elif args.workflow:
        ok = run_workflow(cfg, args.workflow)
    elif args.tier:
        ok = run_tier(cfg, args.tier)
    else:
        p.error("need a tier, --workflow, or --all")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

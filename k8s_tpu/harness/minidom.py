"""minidom: a headless DOM + browser harness for executing the dashboard SPA
under the bundled minijs interpreter (the jsdom analogue for the frontend CI
tier; reference runs its SPA under jest+jsdom —
dashboard/frontend/src/components/App.test.js).

Implements the surface app.js touches: getElementById, createElement,
innerHTML (parsed into a real element tree via html.parser), textContent,
``value`` semantics for input/select/textarea, ``style.display``, inline
on* attribute handlers with ``this``/``event`` binding, event bubbling with
stopPropagation, addEventListener, fetch (host-routed, synchronous
promises), and setInterval/setTimeout with manual test-driven firing.
"""

from __future__ import annotations

import html as html_mod
import json
from html.parser import HTMLParser
from typing import Any, Callable, Optional

from k8s_tpu.harness.minijs.interp import (
    UNDEFINED,
    Environment,
    Interpreter,
    JSException,
    JSObject,
    JSPromise,
    NativeFunction,
    js_to_py,
    js_to_string,
    make_error,
    py_to_js,
)

VOID_TAGS = {"area", "base", "br", "col", "embed", "hr", "img", "input",
             "link", "meta", "source", "track", "wbr"}


class Style:
    """element.style — arbitrary camelCase properties, display is the one
    the SPA routes on."""

    def __init__(self, initial: str = ""):
        self.props: dict[str, str] = {}
        for part in initial.split(";"):
            if ":" in part:
                k, _, v = part.partition(":")
                self.props[_camel(k.strip())] = v.strip()

    def js_get(self, name: str):
        return self.props.get(name, "")

    def js_set(self, name: str, value) -> None:
        self.props[name] = js_to_string(value)


def _camel(css_name: str) -> str:
    parts = css_name.split("-")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


class Text:
    def __init__(self, data: str):
        self.data = data


class Element:
    def __init__(self, tag: str, browser: "Browser"):
        self.tag = tag.lower()
        self.attrs: dict[str, str] = {}
        self.children: list[Any] = []  # Element | Text
        self.parent: Optional[Element] = None
        self.browser = browser
        self.style = Style()
        self._value: Optional[str] = None  # JS-assigned value overrides attrs
        self._listeners: dict[str, list] = {}

    # -- tree ----------------------------------------------------------------

    def append(self, child) -> None:
        if isinstance(child, Element):
            child.parent = self
        self.children.append(child)

    def walk(self):
        yield self
        for c in self.children:
            if isinstance(c, Element):
                yield from c.walk()

    def get_element_by_id(self, el_id: str) -> Optional["Element"]:
        for el in self.walk():
            if el.attrs.get("id") == el_id:
                return el
        return None

    # -- text / html ---------------------------------------------------------

    @property
    def text_content(self) -> str:
        out = []
        for c in self.children:
            if isinstance(c, Text):
                out.append(c.data)
            else:
                out.append(c.text_content)
        return "".join(out)

    def set_text_content(self, text: str) -> None:
        self.children = [Text(text)] if text else []

    @property
    def inner_html(self) -> str:
        return "".join(_serialize(c) for c in self.children)

    def set_inner_html(self, markup: str) -> None:
        self.children = _parse_fragment(markup, self.browser)
        for c in self.children:
            if isinstance(c, Element):
                c.parent = self

    # -- form value semantics -------------------------------------------------

    @property
    def value(self) -> str:
        if self._value is not None:
            return self._value
        if self.tag == "select":
            options = [el for el in self.walk() if el.tag == "option"]
            chosen = next((o for o in options if "selected" in o.attrs),
                          options[0] if options else None)
            if chosen is None:
                return ""
            return chosen.attrs.get("value", chosen.text_content)
        if self.tag == "textarea":
            return self.text_content
        return self.attrs.get("value", "")

    @value.setter
    def value(self, v: str) -> None:
        self._value = v

    # -- events ---------------------------------------------------------------

    def add_event_listener(self, event_type: str, fn) -> None:
        self._listeners.setdefault(event_type, []).append(fn)

    def dispatch(self, event_type: str, bubbles: bool = True) -> "Event":
        event = Event(event_type, self)
        node: Optional[Element] = self
        while node is not None:
            handler_src = node.attrs.get("on" + event_type)
            if handler_src:
                self.browser.run_handler(handler_src, this=node, event=event)
            for fn in node._listeners.get(event_type, []):
                self.browser.interp.call(fn, [event], this=node)
            if event.stopped or not bubbles:
                break
            node = node.parent
        self.browser.interp.drain()
        return event

    # -- JS property protocol -------------------------------------------------

    def js_get(self, name: str):
        simple = {
            "tagName": self.tag.upper(),
            "id": self.attrs.get("id", ""),
            "className": self.attrs.get("class", ""),
            "innerHTML": self.inner_html,
            "textContent": self.text_content,
            "innerText": self.text_content,
            "value": self.value,
            "style": self.style,
            "parentElement": self.parent,
            "parentNode": self.parent,
            "children": py_to_js([]) if not self.children else
                _els(self.children),
            "options": _els([e for e in self.walk() if e.tag == "option"]),
            "dataset": JSObject({k[5:]: v for k, v in self.attrs.items()
                                 if k.startswith("data-")}),
            "checked": "checked" in self.attrs or self._value == "true",
            "disabled": "disabled" in self.attrs,
        }
        if name in simple:
            return simple[name]
        if name == "getAttribute":
            return NativeFunction(
                lambda attr=UNDEFINED:
                    self.attrs.get(js_to_string(attr), None), "getAttribute")
        if name == "setAttribute":
            def set_attr(attr=UNDEFINED, value=UNDEFINED):
                self.attrs[js_to_string(attr)] = js_to_string(value)
                return UNDEFINED
            return NativeFunction(set_attr, "setAttribute")
        if name == "appendChild":
            def append_child(child=UNDEFINED):
                self.append(child)
                return child
            return NativeFunction(append_child, "appendChild")
        if name == "addEventListener":
            def ael(event_type=UNDEFINED, fn=UNDEFINED, *_):
                self.add_event_listener(js_to_string(event_type), fn)
                return UNDEFINED
            return NativeFunction(ael, "addEventListener")
        if name == "click":
            return NativeFunction(lambda: (self.dispatch("click"), UNDEFINED)[1],
                                  "click")
        if name == "querySelector":
            return NativeFunction(
                lambda sel=UNDEFINED:
                    _query(self, js_to_string(sel), first=True),
                "querySelector")
        if name == "querySelectorAll":
            return NativeFunction(
                lambda sel=UNDEFINED:
                    _els(_query(self, js_to_string(sel), first=False)),
                "querySelectorAll")
        if name == "getElementsByTagName":
            return NativeFunction(
                lambda t=UNDEFINED: _els(
                    [e for e in self.walk()
                     if e.tag == js_to_string(t).lower()]),
                "getElementsByTagName")
        if name == "remove":
            def remove():
                if self.parent is not None:
                    self.parent.children.remove(self)
                    self.parent = None
                return UNDEFINED
            return NativeFunction(remove, "remove")
        if name == "focus" or name == "blur":
            return NativeFunction(lambda: UNDEFINED, name)
        return UNDEFINED

    def js_set(self, name: str, value) -> None:
        if name == "innerHTML":
            self.set_inner_html(js_to_string(value))
        elif name in ("textContent", "innerText"):
            self.set_text_content(js_to_string(value))
        elif name == "value":
            self.value = js_to_string(value)
        elif name == "id":
            self.attrs["id"] = js_to_string(value)
        elif name == "className":
            self.attrs["class"] = js_to_string(value)
        elif name == "checked":
            if value:
                self.attrs["checked"] = ""
            else:
                self.attrs.pop("checked", None)
        elif name.startswith("on"):
            # element.onclick = fn
            self.add_event_listener(name[2:], value)
        elif name == "style":
            self.style = Style(js_to_string(value))
        else:
            self.attrs[name] = js_to_string(value)


def _els(items) -> Any:
    from k8s_tpu.harness.minijs.interp import JSArray

    return JSArray(items)


def _query(root: Element, selector: str, first: bool):
    out = []
    for sel in [s.strip() for s in selector.split(",")]:
        for el in root.walk():
            if el is root:
                continue
            if _matches(el, sel) and el not in out:
                out.append(el)
    if first:
        return out[0] if out else None
    return out


def _matches(el: Element, sel: str) -> bool:
    if sel.startswith("#"):
        return el.attrs.get("id") == sel[1:]
    if sel.startswith("."):
        return sel[1:] in el.attrs.get("class", "").split()
    if "[" in sel and sel.endswith("]"):
        tag, _, attr_part = sel.partition("[")
        attr_expr = attr_part[:-1]
        if tag and el.tag != tag.lower():
            return False
        if "=" in attr_expr:
            k, _, v = attr_expr.partition("=")
            return el.attrs.get(k) == v.strip("'\"")
        return attr_expr in el.attrs
    return el.tag == sel.lower()


def _serialize(node) -> str:
    if isinstance(node, Text):
        return html_mod.escape(node.data, quote=False)
    attrs = "".join(
        f' {k}' if v == "" and k in ("selected", "checked", "disabled")
        else f' {k}="{html_mod.escape(v, quote=True)}"'
        for k, v in node.attrs.items())
    if node.tag in VOID_TAGS:
        return f"<{node.tag}{attrs}>"
    return f"<{node.tag}{attrs}>{node.inner_html}</{node.tag}>"


class _FragmentParser(HTMLParser):
    def __init__(self, browser: "Browser"):
        super().__init__(convert_charrefs=True)
        self.browser = browser
        self.root = Element("#fragment", browser)
        self.stack = [self.root]

    def handle_starttag(self, tag, attrs):
        el = Element(tag, self.browser)
        for k, v in attrs:
            el.attrs[k] = v if v is not None else ""
        if "style" in el.attrs:
            el.style = Style(el.attrs["style"])
        self.stack[-1].append(el)
        if tag.lower() not in VOID_TAGS:
            self.stack.append(el)

    def handle_startendtag(self, tag, attrs):
        el = Element(tag, self.browser)
        for k, v in attrs:
            el.attrs[k] = v if v is not None else ""
        self.stack[-1].append(el)

    def handle_endtag(self, tag):
        # close the nearest matching open tag (tolerates minor nesting slop)
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag.lower():
                del self.stack[i:]
                return

    def handle_data(self, data):
        if data:
            self.stack[-1].append(Text(data))


def _parse_fragment(markup: str, browser: "Browser") -> list:
    p = _FragmentParser(browser)
    p.feed(markup)
    p.close()
    return p.root.children


class Event:
    def __init__(self, event_type: str, target: Element):
        self.type = event_type
        self.target = target
        self.stopped = False
        self.default_prevented = False

    def js_get(self, name: str):
        if name == "type":
            return self.type
        if name == "target":
            return self.target
        if name == "stopPropagation":
            def stop():
                self.stopped = True
                return UNDEFINED
            return NativeFunction(stop, "stopPropagation")
        if name == "preventDefault":
            def prevent():
                self.default_prevented = True
                return UNDEFINED
            return NativeFunction(prevent, "preventDefault")
        return UNDEFINED

    def js_set(self, name: str, value) -> None:
        pass


class Document:
    def __init__(self, browser: "Browser"):
        self.browser = browser
        self.root = Element("html", browser)

    def js_get(self, name: str):
        if name == "getElementById":
            return NativeFunction(
                lambda el_id=UNDEFINED:
                    self.root.get_element_by_id(js_to_string(el_id)),
                "getElementById")
        if name == "createElement":
            return NativeFunction(
                lambda tag=UNDEFINED:
                    Element(js_to_string(tag), self.browser),
                "createElement")
        if name == "querySelector":
            return NativeFunction(
                lambda sel=UNDEFINED:
                    _query(self.root, js_to_string(sel), first=True),
                "querySelector")
        if name == "querySelectorAll":
            return NativeFunction(
                lambda sel=UNDEFINED:
                    _els(_query(self.root, js_to_string(sel), first=False)),
                "querySelectorAll")
        if name == "body":
            for el in self.root.walk():
                if el.tag == "body":
                    return el
            return self.root
        if name == "addEventListener":
            return NativeFunction(lambda *a: UNDEFINED, "addEventListener")
        return UNDEFINED

    def js_set(self, name: str, value) -> None:
        pass


class Browser:
    """The test harness: document + script + fetch routing + timers.

    ``fetch_handler(method, url, body) -> (status, payload)`` where payload
    is JSON-ish Python data; provide it before load().  All promises settle
    synchronously so assertions can run immediately after an interaction.
    """

    def __init__(self, fetch_handler: Optional[Callable] = None):
        self.interp = Interpreter()
        self.document = Document(self)
        self.fetch_handler = fetch_handler or (lambda m, u, b: (404, {}))
        self.requests: list[tuple[str, str, Any]] = []
        self.timers: list[dict] = []
        self._timer_id = 0
        self.errors: list[str] = []
        self._install_globals()

    # -- harness API ---------------------------------------------------------

    def load(self, html_text: str, script: str) -> None:
        """Parse the page, then execute its script (as <script src> would)."""
        self.document.root.children = _parse_fragment(html_text, self)
        for c in self.document.root.children:
            if isinstance(c, Element):
                c.parent = self.document.root
        self.interp.run(script)

    def by_id(self, el_id: str) -> Optional[Element]:
        return self.document.root.get_element_by_id(el_id)

    def click(self, el: Element) -> Event:
        return el.dispatch("click")

    def set_value(self, el: Element, value: str, fire: str = "change") -> None:
        el.value = value
        if fire:
            el.dispatch(fire, bubbles=False)

    def fire_timers(self, kind: str = "interval") -> int:
        """Run all registered interval (or timeout) callbacks once."""
        fired = 0
        for t in list(self.timers):
            if t["kind"] != kind:
                continue
            self.interp.call(t["fn"], [])
            fired += 1
            if kind == "timeout":
                self.timers.remove(t)
        self.interp.drain()
        return fired

    def run_handler(self, src: str, this: Element, event: Event) -> None:
        env = Environment(self.interp.globals)
        env.declare("this", this)
        env.declare("event", event)
        try:
            from k8s_tpu.harness.minijs.parser import parse

            program = parse(src)
            self.interp._hoist(program["body"], env)
            for stmt in program["body"]:
                self.interp.exec_stmt(stmt, env)
        except JSException as e:
            self.errors.append(js_to_string(e.value))
            raise

    # -- globals -------------------------------------------------------------

    def _install_globals(self) -> None:
        interp = self.interp
        interp.define("document", self.document)

        def fetch(url=UNDEFINED, opts=UNDEFINED):
            method = "GET"
            body = None
            if isinstance(opts, JSObject):
                method = js_to_string(opts.get("method", "GET")).upper()
                raw = opts.get("body")
                if raw is not None and raw is not UNDEFINED:
                    try:
                        body = json.loads(js_to_string(raw))
                    except ValueError:
                        body = js_to_string(raw)
            url_s = js_to_string(url)
            self.requests.append((method, url_s, body))
            promise = JSPromise(interp)
            try:
                status, payload = self.fetch_handler(method, url_s, body)
            except Exception as e:  # noqa: BLE001 - network-failure analogue
                promise.reject(make_error(str(e), name="TypeError"))
                return promise
            response = _make_response(interp, int(status), payload)
            promise.resolve(response)
            return promise

        interp.define("fetch", NativeFunction(fetch, "fetch"))

        def set_interval(fn=UNDEFINED, ms=0.0, *args):
            self._timer_id += 1
            self.timers.append({"id": self._timer_id, "fn": fn,
                                "ms": float(js_to_py(ms) or 0), "kind": "interval"})
            return float(self._timer_id)

        def set_timeout(fn=UNDEFINED, ms=0.0, *args):
            self._timer_id += 1
            self.timers.append({"id": self._timer_id, "fn": fn,
                                "ms": float(js_to_py(ms) or 0), "kind": "timeout"})
            return float(self._timer_id)

        def clear_timer(timer_id=UNDEFINED):
            tid = js_to_py(timer_id)
            self.timers = [t for t in self.timers if t["id"] != tid]
            return UNDEFINED

        interp.define("setInterval", NativeFunction(set_interval, "setInterval"))
        interp.define("setTimeout", NativeFunction(set_timeout, "setTimeout"))
        interp.define("clearInterval", NativeFunction(clear_timer, "clearInterval"))
        interp.define("clearTimeout", NativeFunction(clear_timer, "clearTimeout"))
        interp.define("window", _Window(self))

        def alert(msg=UNDEFINED):
            self.errors.append(f"alert: {js_to_string(msg)}")
            return UNDEFINED

        interp.define("alert", NativeFunction(alert, "alert"))
        interp.define("confirm", NativeFunction(lambda msg=UNDEFINED: True,
                                                "confirm"))


class _Window:
    def __init__(self, browser: Browser):
        self.browser = browser

    def js_get(self, name: str):
        if name == "document":
            return self.browser.document
        if self.browser.interp.globals.has(name):
            return self.browser.interp.globals.lookup(name)
        return UNDEFINED

    def js_set(self, name: str, value) -> None:
        self.browser.interp.globals.declare(name, value)


_STATUS_TEXT = {200: "OK", 201: "Created", 400: "Bad Request",
                404: "Not Found", 409: "Conflict", 500: "Internal Server Error"}


def _make_response(interp: Interpreter, status: int, payload) -> JSObject:
    response = JSObject()
    response["ok"] = 200 <= status < 300
    response["status"] = float(status)
    response["statusText"] = _STATUS_TEXT.get(status, str(status))

    def json_method():
        p = JSPromise(interp)
        p.resolve(py_to_js(payload))
        return p

    def text_method():
        p = JSPromise(interp)
        p.resolve(json.dumps(payload))
        return p

    response["json"] = NativeFunction(json_method, "json")
    response["text"] = NativeFunction(text_method, "text")
    return response

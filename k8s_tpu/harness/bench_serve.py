"""Serving throughput benchmark: N concurrent closed-loop clients vs the
resident HTTP inference server (models/server.py), single-flight vs the
continuous-batching engine (models/engine.py).

    python -m k8s_tpu.harness.bench_serve --concurrency 8 --slots 8

Both phases run the SAME tiny randomly-initialized transformer in the
same process over real HTTP (ThreadingHTTPServer + stdlib clients), so
the comparison isolates the serving architecture:

- **single_flight**: ``slots=0`` — the legacy one-lock path, every
  request a whole-generation program, requests fully serialized;
- **batched**: ``slots=N`` — slot-based continuous batching, one shared
  decode step advancing all active slots, join/retire between steps.

The workload is deliberately adversarial for the serialized path: client
0 issues LONG generations (``--max-new-long``) while the rest issue
short ones, so single-flight p99 for short requests degrades to
"wait for the long generation", while iteration-level scheduling lets
shorts retire mid-flight.  Emits one JSON line (bench.py contract) with
aggregate tokens/s per phase, the speedup, p50/p99 request latency
(overall and shorts-only), and the engine's batch-occupancy timeline;
``--out`` additionally writes the full JSON artifact.

Round 6 adds the PRODUCTION-SHAPED phases (``--sampled``, on by
default): 80% of requests share a templated prompt prefix and all carry
``temperature>0`` with per-request seeds — the mix that used to
serialize completely on the engine's exclusive single-flight lane.
Phase ``sampled_exclusive`` routes sampling exclusively with prefix
reuse off (the pre-round-6 engine); ``sampled_batched`` rides the slot
lanes with the radix prefix cache on.  Every phase records its compile
counts (bucket prefill programs, batched decode programs, whole-
generation exclusive programs) and the prefix-cache hit rate measured
AFTER warmup, so reuse wins are not conflated with compile warming; a
fixed-seed equivalence spot check asserts the two sampled routings emit
identical tokens.

CPU-provable: everything runs on the host platform; no TPU required.
Numbers are advisory trend data — ci_config.yaml wires this into the
non-gating bench_smoke tier via ``bench_operator --serve``.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
import urllib.request

log = logging.getLogger(__name__)

# mixed prompt lengths exercising several prefill buckets (13 = 8+4+1 ...)
PROMPT_LENGTHS = (4, 6, 13, 21)


def _downsample(timeline: list, points: int) -> list:
    """Evenly-strided subset of a (step, occupancy) timeline, keeping the
    final sample so the retire tail is visible."""
    if len(timeline) <= points:
        return [list(t) for t in timeline]
    stride = len(timeline) / points
    out = [list(timeline[int(i * stride)]) for i in range(points)]
    out.append(list(timeline[-1]))
    return out


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def build_model(seed: int = 0):
    """CPU-benchable causal LM with byte vocab (256).  Sized so decode is
    PARAM-BOUND like real serving (streaming ~10 MB of weights per
    unbatched token): hidden 256 / 4 layers makes a batch-8 step cost
    ~2x one fused-scan token, so continuous batching wins on shared
    weight reads — the same mechanism as on TPU — rather than on
    framework-overhead artifacts of a toy model."""
    import jax
    import jax.numpy as jnp

    from k8s_tpu.models.transformer import Transformer, TransformerConfig

    config = TransformerConfig(
        vocab_size=256, hidden=256, ffn_hidden=512, layers=4, heads=8,
        kv_heads=8, max_seq_len=128, dtype=jnp.float32, remat=False)
    params = Transformer(config).init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return config, params


def _prompt(rank: int, length: int) -> list[int]:
    # deterministic per (client, length) so both phases see identical work
    return [(rank * 31 + i * 7 + length) % 256 for i in range(length)]


def _template(length: int) -> list[int]:
    """The shared system-prompt prefix of the sampled phases."""
    return [(i * 5 + 3) % 256 for i in range(length)]


def _shared_prompt(rank: int, i: int, template_len: int,
                   tail_len: int, shared: bool) -> list[int]:
    """Templated traffic: ``shared`` requests are the common template
    plus a per-(client, request) unique tail; the rest are fully unique
    prompts of the same total length (so both routings compile the same
    shapes and only REUSE differs)."""
    if shared:
        tail = [(rank * 17 + i * 13 + j * 7 + 1) % 256
                for j in range(tail_len)]
        return _template(template_len) + tail
    return [(rank * 37 + i * 101 + j * 7 + 11) % 256
            for j in range(template_len + tail_len)]


def _post(url: str, payload: dict, timeout: float = 300.0) -> dict:
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def run_phase(config, params, *, slots: int, concurrency: int,
              requests_per_client: int, max_new_short: int,
              max_new_long: int, queue_limit: int = 1024,
              temperature: float = 0.0,
              batch_sampling: bool = True,
              prefix_blocks: int | None = None,
              shared_frac: float = 0.0, template_len: int = 40,
              tail_len: int = 6, mode: str | None = None) -> dict:
    """One closed-loop phase: start a server, warm every program shape,
    then hammer it with ``concurrency`` clients and measure.

    ``shared_frac > 0`` switches to the templated workload: that
    fraction of requests shares a ``template_len``-token prefix (the
    rest are unique same-length prompts), every request carries
    ``temperature`` with a per-request seed, and the phase reports the
    prefix-cache hit rate of the MEASURED section (warmup pre-seeds the
    tree, then counters are snapshotted — reuse wins are not conflated
    with compile warming)."""
    from k8s_tpu.models import decode as decode_lib
    from k8s_tpu.models.server import LmServer, serve
    from k8s_tpu.util.metrics import Registry

    lm = LmServer(config=config, params=params, slots=slots,
                  queue_limit=queue_limit, batch_sampling=batch_sampling,
                  prefix_blocks=prefix_blocks, registry=Registry())
    httpd = serve(lm)
    url = "http://%s:%d" % httpd.server_address[:2]
    gen_programs0 = decode_lib._cached_generate_fn.cache_info().currsize
    try:
        # warmup: compile every (prompt_len, max_new) shape ANY client
        # will issue — the long client cycles through all prompt lengths
        # too — so the measured section is compile-free in both phases
        if shared_frac > 0:
            for shared in (True, False):
                _post(url, {"tokens": _shared_prompt(
                    99, 99, template_len, tail_len, shared),
                    "max_new_tokens": max_new_short,
                    "temperature": temperature, "seed": 99})
            # warm the copy-on-write program too: a mid-block partial
            # match (truncated template + unique tail) CoWs the
            # divergence block, so that compile never lands inside the
            # measured section either
            cut = (template_len // 2) | 1  # odd: never block-aligned
            _post(url, {"tokens": _template(template_len)[:cut]
                        + [250, 251, 252],
                        "max_new_tokens": max_new_short,
                        "temperature": temperature, "seed": 98})
        else:
            for length in PROMPT_LENGTHS:
                for max_new in (max_new_short, max_new_long):
                    _post(url, {"tokens": _prompt(0, length),
                                "max_new_tokens": max_new,
                                "temperature": temperature})
        warm_stats = lm.engine.stats() if lm.engine is not None else {}

        lat_all: list[float] = []
        lat_short: list[float] = []
        tokens_out = [0]
        errors: list[str] = []
        lock = threading.Lock()
        barrier = threading.Barrier(concurrency + 1)

        def client(rank: int) -> None:
            import http.client

            # greedy phases: one long-generation client exposes the
            # head-of-line price.  Sampled phases: a uniform short mix —
            # the headline there is aggregate tokens/s under the
            # production traffic shape, and a single long straggler
            # would only measure the tail of an emptying batch.
            is_long = rank == 0 and shared_frac == 0
            max_new = max_new_long if is_long else max_new_short
            # one keep-alive connection per client: a real closed-loop
            # client reuses its socket, and per-request TCP + server
            # thread churn would otherwise dominate the tiny-model math
            conn = http.client.HTTPConnection(
                "%s:%d" % httpd.server_address[:2], timeout=300)
            barrier.wait()
            # desynchronize starts: a perfectly phase-locked client fleet
            # is a load-generator artifact (every request joins and
            # retires in one wave, so the batch convoys at low occupancy
            # and the "concurrent" load is really sequential bursts);
            # a few ms of per-rank jitter restores steady-state arrivals
            time.sleep(rank * 0.005)
            try:
                for i in range(requests_per_client):
                    if shared_frac > 0:
                        # deterministic split accurate to 1% for ANY
                        # fraction (a modulus of round(1/(1-f)) would
                        # collapse to 0% shared for f <= 0.33): the SAME
                        # mix hits every phase, so only routing differs
                        shared = ((rank * 37 + i * 11) % 100) \
                            < round(shared_frac * 100)
                        payload = {"tokens": _shared_prompt(
                            rank, i, template_len, tail_len, shared),
                            "max_new_tokens": max_new,
                            "temperature": temperature,
                            "seed": rank * 1000 + i}
                    else:
                        length = PROMPT_LENGTHS[(rank + i)
                                                % len(PROMPT_LENGTHS)]
                        payload = {"tokens": _prompt(rank, length),
                                   "max_new_tokens": max_new}
                    body = json.dumps(payload).encode()
                    t0 = time.monotonic()
                    try:
                        conn.request(
                            "POST", "/v1/generate", body=body,
                            headers={"Content-Type": "application/json"})
                        resp = conn.getresponse()
                        out = json.loads(resp.read())
                        assert resp.status == 200, out
                    except Exception as e:  # noqa: BLE001 - count, don't crash
                        with lock:
                            errors.append(f"{type(e).__name__}: {e}")
                        continue
                    dt = time.monotonic() - t0
                    with lock:
                        lat_all.append(dt)
                        if not is_long:
                            lat_short.append(dt)
                        tokens_out[0] += len(out["tokens"])
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(r,), daemon=True)
                   for r in range(concurrency)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.monotonic()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0

        engine_stats = lm.engine.stats() if lm.engine is not None else {}
        lat_all.sort()
        lat_short.sort()
        occ = [o for _, o in engine_stats.get("occupancy_timeline", [])]
        # per-phase compile inventory + MEASURED-section prefix stats
        # (deltas vs the post-warmup snapshot: reuse wins must not be
        # conflated with compile warming)
        compile_counts = {
            "prefill_programs": engine_stats.get("prefill_programs", []),
            "decode_programs": engine_stats.get("decode_programs", 0),
            "whole_gen_programs":
                decode_lib._cached_generate_fn.cache_info().currsize
                - gen_programs0,
        }
        hits = engine_stats.get("prefix_hits", 0) \
            - warm_stats.get("prefix_hits", 0)
        prefix = {
            "hits": hits,
            "hit_rate": round(hits / max(1, len(lat_all)), 3),
            "tokens_saved": engine_stats.get("prefix_tokens_saved", 0)
            - warm_stats.get("prefix_tokens_saved", 0),
            "cow_copies": engine_stats.get("cow_copies", 0),
            "tree_nodes": engine_stats.get("tree_nodes", 0),
            "blocks_in_use": engine_stats.get("blocks_in_use", 0),
            "pool_blocks": engine_stats.get("pool_blocks", 0),
        }
        return {
            "mode": mode or ("batched" if slots > 0 else "single_flight"),
            "slots": slots,
            "temperature": temperature,
            "batch_sampling": bool(batch_sampling) and slots > 0,
            "shared_frac": shared_frac,
            "compile": compile_counts,
            "prefix": prefix,
            "requests": len(lat_all),
            "errors": errors[:5],
            "wall_s": round(wall, 3),
            "tokens": tokens_out[0],
            "tokens_per_s": round(tokens_out[0] / max(wall, 1e-9), 1),
            "latency_p50_s": round(_quantile(lat_all, 0.50), 4),
            "latency_p99_s": round(_quantile(lat_all, 0.99), 4),
            "short_p99_s": round(_quantile(lat_short, 0.99), 4),
            "mean_batch_occupancy": round(sum(occ) / len(occ), 2)
            if occ else None,
            # downsampled (step, active-slots) curve: how full the batch
            # stayed over the run, compact enough for the JSON line
            "occupancy_timeline": _downsample(
                engine_stats.get("occupancy_timeline", []), 32),
            "decode_steps": engine_stats.get("steps"),
            "prefill_programs": engine_stats.get("prefill_programs"),
        }
    finally:
        httpd.shutdown()
        lm.close()


def check_sampled_equivalence(config, params, template_len: int = 40,
                              tail_len: int = 6) -> bool:
    """Fixed-seed spot check over real HTTP: the batched sampling lane
    and the exclusive lane must emit IDENTICAL tokens — the bench's
    speedup claim is only meaningful if the routing is output-invariant."""
    from k8s_tpu.models.server import LmServer, serve
    from k8s_tpu.util.metrics import Registry

    payload = {"tokens": _shared_prompt(3, 1, template_len, tail_len,
                                        True),
               "max_new_tokens": 8, "temperature": 1.0, "seed": 7}
    outs = []
    for batch_sampling in (True, False):
        lm = LmServer(config=config, params=params, slots=2,
                      queue_limit=8, batch_sampling=batch_sampling,
                      registry=Registry())
        httpd = serve(lm)
        try:
            outs.append(_post("http://%s:%d" % httpd.server_address[:2],
                              payload))
        finally:
            httpd.shutdown()
            lm.close()
    return outs[0] == outs[1]


def run_bench(concurrency: int = 16, slots: int = 8,
              requests_per_client: int = 4, max_new_short: int = 32,
              max_new_long: int = 64, seed: int = 0,
              sampled: bool = True, shared_frac: float = 0.8) -> dict:
    """Single-flight vs continuous batching over the same model/workload
    (the PR-5 greedy phases), plus the round-6 production mix: 80%
    shared-prefix traffic at temperature>0, exclusive-lane sampling (the
    pre-round-6 engine) vs the batched sampling lane with prefix reuse.
    Returns the JSON-able comparison dict."""
    config, params = build_model(seed)
    single = run_phase(config, params, slots=0, concurrency=concurrency,
                       requests_per_client=requests_per_client,
                       max_new_short=max_new_short,
                       max_new_long=max_new_long)
    # prefix reuse OFF in the greedy comparison: the slots=0 baseline
    # cannot have a prefix cache, so leaving it on would fold reuse wins
    # into the "continuous batching vs single flight" claim (the warmup
    # even pre-seeds client 0's exact prompts).  The sampled phases
    # below measure reuse explicitly.
    batched = run_phase(config, params, slots=slots,
                        concurrency=concurrency,
                        requests_per_client=requests_per_client,
                        max_new_short=max_new_short,
                        max_new_long=max_new_long, prefix_blocks=0)
    speedup = batched["tokens_per_s"] / max(single["tokens_per_s"], 1e-9)
    result = {
        "metric": "serve_tokens_per_s",
        "value": batched["tokens_per_s"],
        "unit": "tok/s",
        "concurrency": concurrency,
        "requests_per_client": requests_per_client,
        "max_new_short": max_new_short,
        "max_new_long": max_new_long,
        "single_flight": single,
        "batched": batched,
        "speedup": round(speedup, 2),
        # iteration-level scheduling headline: short requests behind a
        # long generation (p99) — serialized vs continuous batching
        "short_p99_single_s": single["short_p99_s"],
        "short_p99_batched_s": batched["short_p99_s"],
    }
    if sampled:
        # the production-shaped mix: templated prompts, temperature>0.
        # Baseline = the pre-round-6 engine (sampling exclusive, no
        # prefix reuse); candidate = batched sampling + radix reuse.
        # Load is raised past the greedy phases' (2x the clients): a
        # serialized baseline is load-invariant while the batched lane
        # exists exactly to convert backlog into occupancy.
        sampled_kw = dict(
            slots=slots, concurrency=concurrency * 2,
            requests_per_client=requests_per_client,
            max_new_short=max_new_short, max_new_long=max_new_long,
            temperature=1.0, shared_frac=shared_frac)
        exclusive = run_phase(config, params, batch_sampling=False,
                              prefix_blocks=0, mode="sampled_exclusive",
                              **sampled_kw)
        promoted = run_phase(config, params, batch_sampling=True,
                             prefix_blocks=None, mode="sampled_batched",
                             **sampled_kw)
        result["sampled_exclusive"] = exclusive
        result["sampled_batched"] = promoted
        result["sampled_speedup"] = round(
            promoted["tokens_per_s"]
            / max(exclusive["tokens_per_s"], 1e-9), 2)
        result["sampled_shared_frac"] = shared_frac
        result["sampled_equivalence_ok"] = check_sampled_equivalence(
            config, params)
    # Embedded assertions (the bench_churn.json contract, ISSUE 8
    # drive-by: every bench artifact reports failures the same way): a
    # violated invariant attaches a ``failures`` field and raises with
    # the full result on the exception, so the artifact still lands in
    # the non-gating CI tier for whoever debugs the regression.
    failures: list[str] = []
    for phase in (single, batched,
                  result.get("sampled_exclusive") or {},
                  result.get("sampled_batched") or {}):
        if phase.get("errors"):
            failures.append(
                f"phase {phase.get('mode')}: request errors "
                f"{phase['errors']}")
    if sampled and not result["sampled_equivalence_ok"]:
        failures.append(
            "sampled routing not output-invariant: batched sampling lane "
            "and exclusive lane emitted different tokens at a fixed seed")
    if failures:
        result["failures"] = failures
        err = RuntimeError("serve bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", type=int, default=16,
                   help="closed-loop client threads (>= 2; client 0 "
                   "issues long generations; > slots keeps a backlog so "
                   "slots stay fed through client turnaround)")
    p.add_argument("--slots", type=int, default=8,
                   help="decode slots for the batched phase")
    p.add_argument("--requests", type=int, default=4,
                   help="requests per client per phase")
    p.add_argument("--max-new-short", type=int, default=32)
    p.add_argument("--max-new-long", type=int, default=64,
                   help="the long-client generation length (the head-of-"
                   "line blocker for the serialized baseline)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sampled", type=int, choices=(0, 1), default=1,
                   help="also run the shared-prefix temperature>0 "
                   "phases: exclusive-lane sampling vs the batched "
                   "sampling lane with prefix reuse (default on)")
    p.add_argument("--shared-frac", type=float, default=0.8,
                   help="fraction of sampled-phase requests sharing the "
                   "templated prompt prefix")
    p.add_argument("--out", default=None,
                   help="also write the JSON result to this path "
                   "(bench artifact)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)

    def _write(payload: dict) -> None:
        line = json.dumps(payload)
        print(line)
        if args.out:
            import os

            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                f.write(line + "\n")

    try:
        result = run_bench(concurrency=args.concurrency, slots=args.slots,
                           requests_per_client=args.requests,
                           max_new_short=args.max_new_short,
                           max_new_long=args.max_new_long, seed=args.seed,
                           sampled=bool(args.sampled),
                           shared_frac=args.shared_frac)
    except RuntimeError as e:
        # artifact written on failure too, ``failures`` field included
        # (the bench_churn.json contract)
        partial = getattr(e, "result", None)
        if partial is not None:
            _write(partial)
        raise
    _write(result)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""Serving throughput benchmark: N concurrent closed-loop clients vs the
resident HTTP inference server (models/server.py), single-flight vs the
continuous-batching engine (models/engine.py).

    python -m k8s_tpu.harness.bench_serve --concurrency 8 --slots 8

Both phases run the SAME tiny randomly-initialized transformer in the
same process over real HTTP (ThreadingHTTPServer + stdlib clients), so
the comparison isolates the serving architecture:

- **single_flight**: ``slots=0`` — the legacy one-lock path, every
  request a whole-generation program, requests fully serialized;
- **batched**: ``slots=N`` — slot-based continuous batching, one shared
  decode step advancing all active slots, join/retire between steps.

The workload is deliberately adversarial for the serialized path: client
0 issues LONG generations (``--max-new-long``) while the rest issue
short ones, so single-flight p99 for short requests degrades to
"wait for the long generation", while iteration-level scheduling lets
shorts retire mid-flight.  Emits one JSON line (bench.py contract) with
aggregate tokens/s per phase, the speedup, p50/p99 request latency
(overall and shorts-only), and the engine's batch-occupancy timeline;
``--out`` additionally writes the full JSON artifact.

Round 6 adds the PRODUCTION-SHAPED phases (``--sampled``, on by
default): 80% of requests share a templated prompt prefix and all carry
``temperature>0`` with per-request seeds — the mix that used to
serialize completely on the engine's exclusive single-flight lane.
Phase ``sampled_exclusive`` routes sampling exclusively with prefix
reuse off (the pre-round-6 engine); ``sampled_batched`` rides the slot
lanes with the radix prefix cache on.  Every phase records its compile
counts (bucket prefill programs, batched decode programs, whole-
generation exclusive programs) and the prefix-cache hit rate measured
AFTER warmup, so reuse wins are not conflated with compile warming; a
fixed-seed equivalence spot check asserts the two sampled routings emit
identical tokens.

Round 9 adds the SPECULATIVE phases (``--spec``, on by default):
every request carries ``speculative: draft_k`` over repetitive/
structured prompts (cyclic token runs — the traffic prompt-lookup
drafting is strong on).  Phase ``spec_exclusive`` routes speculation
through the exclusive single-flight lane (the pre-round-9 engine:
whole-generation programs, one request at a time between batch
iterations); ``spec_batched`` rides the write-masked variable-width
slot lanes, every spec slot verifying its draft chunk in the same
batched call.  The phase records the measured draft-acceptance rate and
mean accepted drafts per verify step, and a fixed-seed equivalence spot
check asserts the two routings emit identical tokens (greedy AND
sampled speculation).  The embedded assertions additionally pin the
round-9 perf contract: spec_batched >= 1.5x spec_exclusive aggregate
tokens/s, batched greedy no slower than single-flight (the paged
decode step must preserve the continuous-batching win), and compile
counts bounded by the engine's static program sets.

Round 12 adds per-request observability: the engine phases run under a
fresh request lifecycle recorder (models/requestlog.py), cleared at the
warmup boundary, so every phase's artifact carries TTFT/TPOT/queue-wait
p50/p99 of the MEASURED section plus a ``requests_audit`` block
(dominant-phase counts, engine step-ledger rollup, slowest timelines —
the requests_audit.json artifact ``bench_operator --requests-audit-out``
writes).  An identical recorder-OFF batched phase pins the overhead:
recorder-ON batched tokens/s must stay within 3% of recorder-OFF (an
EMBEDDED assertion — the recorder must pay for itself like the compile
ledger's lazy fingerprinting did).

CPU-provable: everything runs on the host platform; no TPU required.
Numbers are advisory trend data — ci_config.yaml wires this into the
non-gating bench_smoke tier via ``bench_operator --serve``.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
import urllib.request

log = logging.getLogger(__name__)

# mixed prompt lengths exercising several prefill buckets (13 = 8+4+1 ...)
PROMPT_LENGTHS = (4, 6, 13, 21)


def _downsample(timeline: list, points: int) -> list:
    """Evenly-strided subset of a (step, occupancy) timeline, keeping the
    final sample so the retire tail is visible."""
    if len(timeline) <= points:
        return [list(t) for t in timeline]
    stride = len(timeline) / points
    out = [list(timeline[int(i * stride)]) for i in range(points)]
    out.append(list(timeline[-1]))
    return out


# the shared nearest-rank quantile (also the request recorder's) — one
# implementation, so bench and /debug/requests percentiles cannot drift
from k8s_tpu.util.util import quantile_nearest as _quantile  # noqa: E402


def build_model(seed: int = 0, hidden: int = 256, layers: int = 4):
    """CPU-benchable causal LM with byte vocab (256).  Sized so decode is
    PARAM-BOUND like real serving (streaming ~10 MB of weights per
    unbatched token): hidden 256 / 4 layers makes a batch-8 step cost
    ~2x one fused-scan token, so continuous batching wins on shared
    weight reads — the same mechanism as on TPU — rather than on
    framework-overhead artifacts of a toy model.  The speculative
    phases pass ``hidden=512``: a draft_k-wide verify chunk has
    draft_k x the arithmetic intensity of a 1-wide step, so keeping THAT
    phase param-bound (where batching wins on shared weight streams)
    needs proportionally more weights per step — at hidden 256 a CPU
    batch-8 verify is pure-compute-bound and measures ALU contention,
    not the serving mechanism."""
    import jax
    import jax.numpy as jnp

    from k8s_tpu.models.transformer import Transformer, TransformerConfig

    config = TransformerConfig(
        vocab_size=256, hidden=hidden, ffn_hidden=2 * hidden,
        layers=layers, heads=8, kv_heads=8, max_seq_len=128,
        dtype=jnp.float32, remat=False)
    params = Transformer(config).init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return config, params


def _prompt(rank: int, length: int) -> list[int]:
    # deterministic per (client, length) so both phases see identical work
    return [(rank * 31 + i * 7 + length) % 256 for i in range(length)]


def _template(length: int) -> list[int]:
    """The shared system-prompt prefix of the sampled phases."""
    return [(i * 5 + 3) % 256 for i in range(length)]


SPEC_PROMPT_LEN = 30  # one fixed shape per spec phase: the exclusive
# lane jit-traces per prompt length, so a single length keeps its
# whole-generation program count at 1 and the comparison compile-fair


def _spec_prompt(rank: int, i: int, length: int = SPEC_PROMPT_LEN
                 ) -> list[int]:
    """Repetitive/structured prompts for the speculative phases: a
    6-token cycle repeated to ``length`` — the 2-gram structure
    prompt-lookup drafting copies from.  Per-(client, request) cycle
    content keeps requests distinct while every shape stays fixed."""
    cycle = [(rank * 29 + i * 17 + j * 11 + 3) % 256 for j in range(6)]
    return [cycle[j % 6] for j in range(length)]


def _grounded_spec_prompts(config, params, n: int = 8, base_len: int = 8,
                           embed: int = SPEC_PROMPT_LEN - 8
                           ) -> list[list[int]]:
    """The speculative phases' workload: GROUNDED prompts — each embeds
    the model's own greedy continuation of a short base, so the served
    generation reproduces a span already present in the context (greedy
    decoding is self-consistent under prefix extension).  This is the
    traffic class prompt-lookup drafting targets — extraction/
    summarization/templated generation whose output copies context
    spans — and it is what "structured prompts where drafting is
    strong" means operationally.  Bases whose continuation never
    settles into a repetitive tail are skipped (a random-init model's
    chaotic trajectories draft at chance; selecting drafting-friendly
    traffic biases NEITHER lane — both phases serve the identical mix
    and the lane comparison is the claim).  All prompts share one
    length so the exclusive lane compiles exactly one whole-generation
    program."""
    import numpy as np

    from k8s_tpu.models import decode as decode_lib

    out: list[list[int]] = []
    seed = 0
    while len(out) < n and seed < 16 * n:
        base = [(seed * 29 + j * 11 + 3) % 256 for j in range(base_len)]
        cont = [int(t) for t in np.asarray(decode_lib.generate(
            config, params, np.asarray(base, np.int32)[None],
            embed + 12))[0]]
        if len(set(cont[embed:])) <= 2:  # repetitive tail: drafts track
            out.append(base + cont[:embed])
        seed += 1
    # pathological weights: fall back to cyclic prompts rather than spin
    while len(out) < n:
        out.append(_spec_prompt(len(out), 0))
    return out


def _shared_prompt(rank: int, i: int, template_len: int,
                   tail_len: int, shared: bool) -> list[int]:
    """Templated traffic: ``shared`` requests are the common template
    plus a per-(client, request) unique tail; the rest are fully unique
    prompts of the same total length (so both routings compile the same
    shapes and only REUSE differs)."""
    if shared:
        tail = [(rank * 17 + i * 13 + j * 7 + 1) % 256
                for j in range(tail_len)]
        return _template(template_len) + tail
    return [(rank * 37 + i * 101 + j * 7 + 11) % 256
            for j in range(template_len + tail_len)]


def _post(url: str, payload: dict, timeout: float = 300.0) -> dict:
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def run_phase(config, params, *, slots: int, concurrency: int,
              requests_per_client: int, max_new_short: int,
              max_new_long: int, queue_limit: int = 1024,
              temperature: float = 0.0,
              batch_sampling: bool = True,
              batch_spec: bool = True, spec_k: int = 0,
              spec_prompts: list | None = None,
              prefix_blocks: int | None = None,
              shared_frac: float = 0.0, template_len: int = 40,
              tail_len: int = 6, mode: str | None = None,
              request_log: bool = False) -> dict:
    """One closed-loop phase: start a server, warm every program shape,
    then hammer it with ``concurrency`` clients and measure.

    ``shared_frac > 0`` switches to the templated workload: that
    fraction of requests shares a ``template_len``-token prefix (the
    rest are unique same-length prompts), every request carries
    ``temperature`` with a per-request seed, and the phase reports the
    prefix-cache hit rate of the MEASURED section (warmup pre-seeds the
    tree, then counters are snapshotted — reuse wins are not conflated
    with compile warming).

    ``request_log`` runs the phase under a fresh request lifecycle
    recorder (ISSUE 12): the recorder is cleared after warmup so the
    reported TTFT/TPOT/queue-wait percentiles cover the MEASURED
    section, and the phase dict gains ``request_phases`` (the
    percentiles) plus ``requests_audit`` (dominant-phase counts, engine
    step-ledger rollup, slowest timelines)."""
    from k8s_tpu.models import decode as decode_lib
    from k8s_tpu.models import requestlog
    from k8s_tpu.models.server import LmServer, serve
    from k8s_tpu.util.metrics import Registry

    import os

    rec = None
    prev_rec = requestlog.active()
    # the env knob is neutralized for BOTH arms during engine binding:
    # with K8S_TPU_REQUEST_LOG=1 ambient (the workload/e2e tier env),
    # Engine.__init__'s maybe_active() would auto-create a recorder and
    # turn the recorder-OFF baseline into a second ON arm — the 3%
    # overhead assertion would compare ON vs ON and never fire
    prev_env = os.environ.pop(requestlog.ENV_ENABLE, None)
    if request_log:
        # activated BEFORE LmServer: the engine binds the active
        # recorder at construction
        rec = requestlog.RequestRecorder()
        requestlog.set_active(rec)
    else:
        requestlog.set_active(None)
    try:
        lm = LmServer(config=config, params=params, slots=slots,
                      queue_limit=queue_limit,
                      batch_sampling=batch_sampling,
                      batch_spec=batch_spec,
                      prefix_blocks=prefix_blocks, registry=Registry())
    finally:
        if prev_env is not None:
            os.environ[requestlog.ENV_ENABLE] = prev_env
    httpd = serve(lm)
    url = "http://%s:%d" % httpd.server_address[:2]
    gen_programs0 = decode_lib._cached_generate_fn.cache_info().currsize
    spec_programs0 = decode_lib.cached_speculative_fn.cache_info().currsize
    try:
        # warmup: compile every (prompt_len, max_new) shape ANY client
        # will issue — the long client cycles through all prompt lengths
        # too — so the measured section is compile-free in both phases
        if spec_k > 0:
            # one spec shape per phase: warms the exclusive lane's
            # whole-generation program OR the batched lane's prefill
            # buckets + variable-width verify program, depending on the
            # batch_spec routing under test
            if spec_prompts is None:
                spec_prompts = [_spec_prompt(r, 0) for r in range(8)]
            _post(url, {"tokens": spec_prompts[0],
                        "max_new_tokens": max_new_short,
                        "temperature": temperature,
                        "speculative": spec_k, "seed": 99})
        elif shared_frac > 0:
            for shared in (True, False):
                _post(url, {"tokens": _shared_prompt(
                    99, 99, template_len, tail_len, shared),
                    "max_new_tokens": max_new_short,
                    "temperature": temperature, "seed": 99})
            # warm the copy-on-write program too: a mid-block partial
            # match (truncated template + unique tail) CoWs the
            # divergence block, so that compile never lands inside the
            # measured section either
            cut = (template_len // 2) | 1  # odd: never block-aligned
            _post(url, {"tokens": _template(template_len)[:cut]
                        + [250, 251, 252],
                        "max_new_tokens": max_new_short,
                        "temperature": temperature, "seed": 98})
        else:
            for length in PROMPT_LENGTHS:
                for max_new in (max_new_short, max_new_long):
                    _post(url, {"tokens": _prompt(0, length),
                                "max_new_tokens": max_new,
                                "temperature": temperature})
        warm_stats = lm.engine.stats() if lm.engine is not None else {}
        if rec is not None:
            # warmup boundary: the reported percentiles must cover the
            # measured section only (compile warming is not latency)
            rec.clear()

        lat_all: list[float] = []
        lat_short: list[float] = []
        tokens_out = [0]
        errors: list[str] = []
        lock = threading.Lock()
        barrier = threading.Barrier(concurrency + 1)

        def client(rank: int) -> None:
            import http.client

            # greedy phases: one long-generation client exposes the
            # head-of-line price.  Sampled/spec phases: a uniform short
            # mix — the headline there is aggregate tokens/s under the
            # production traffic shape, and a single long straggler
            # would only measure the tail of an emptying batch.
            is_long = rank == 0 and shared_frac == 0 and spec_k == 0
            max_new = max_new_long if is_long else max_new_short
            # one keep-alive connection per client: a real closed-loop
            # client reuses its socket, and per-request TCP + server
            # thread churn would otherwise dominate the tiny-model math
            conn = http.client.HTTPConnection(
                "%s:%d" % httpd.server_address[:2], timeout=300)
            barrier.wait()
            # desynchronize starts: a perfectly phase-locked client fleet
            # is a load-generator artifact (every request joins and
            # retires in one wave, so the batch convoys at low occupancy
            # and the "concurrent" load is really sequential bursts);
            # a few ms of per-rank jitter restores steady-state arrivals
            time.sleep(rank * 0.005)
            try:
                for i in range(requests_per_client):
                    if spec_k > 0:
                        toks = spec_prompts[(rank + i)
                                            % len(spec_prompts)]
                        payload = {"tokens": toks,
                                   "max_new_tokens": max_new,
                                   "temperature": temperature,
                                   "speculative": spec_k,
                                   "seed": rank * 1000 + i}
                    elif shared_frac > 0:
                        # deterministic split accurate to 1% for ANY
                        # fraction (a modulus of round(1/(1-f)) would
                        # collapse to 0% shared for f <= 0.33): the SAME
                        # mix hits every phase, so only routing differs
                        shared = ((rank * 37 + i * 11) % 100) \
                            < round(shared_frac * 100)
                        payload = {"tokens": _shared_prompt(
                            rank, i, template_len, tail_len, shared),
                            "max_new_tokens": max_new,
                            "temperature": temperature,
                            "seed": rank * 1000 + i}
                    else:
                        length = PROMPT_LENGTHS[(rank + i)
                                                % len(PROMPT_LENGTHS)]
                        payload = {"tokens": _prompt(rank, length),
                                   "max_new_tokens": max_new}
                    body = json.dumps(payload).encode()
                    t0 = time.monotonic()
                    try:
                        conn.request(
                            "POST", "/v1/generate", body=body,
                            headers={"Content-Type": "application/json"})
                        resp = conn.getresponse()
                        out = json.loads(resp.read())
                        assert resp.status == 200, out
                    except Exception as e:  # noqa: BLE001 - count, don't crash
                        with lock:
                            errors.append(f"{type(e).__name__}: {e}")
                        continue
                    dt = time.monotonic() - t0
                    with lock:
                        lat_all.append(dt)
                        if not is_long:
                            lat_short.append(dt)
                        tokens_out[0] += len(out["tokens"])
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(r,), daemon=True)
                   for r in range(concurrency)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.monotonic()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0

        engine_stats = lm.engine.stats() if lm.engine is not None else {}
        lat_all.sort()
        lat_short.sort()
        occ = [o for _, o in engine_stats.get("occupancy_timeline", [])]
        # per-phase compile inventory + MEASURED-section prefix stats
        # (deltas vs the post-warmup snapshot: reuse wins must not be
        # conflated with compile warming)
        compile_counts = {
            "prefill_programs": engine_stats.get("prefill_programs", []),
            "decode_programs": engine_stats.get("decode_programs", 0),
            "whole_gen_programs":
                decode_lib._cached_generate_fn.cache_info().currsize
                - gen_programs0,
            "whole_gen_spec_programs":
                decode_lib.cached_speculative_fn.cache_info().currsize
                - spec_programs0,
        }
        # runtime compile ledger (ISSUE 11): when K8S_TPU_COMPILE_LEDGER
        # is on, this phase's declared seams — prefill buckets, fused
        # decode widths, spec pairs, whole-gen bound — with observed
        # program counts; run_bench asserts none went over budget (the
        # ledger-read replacement for the hand-rolled inventory bound)
        compile_ledger = lm.compile_audit()
        hits = engine_stats.get("prefix_hits", 0) \
            - warm_stats.get("prefix_hits", 0)
        prefix = {
            "hits": hits,
            "hit_rate": round(hits / max(1, len(lat_all)), 3),
            "tokens_saved": engine_stats.get("prefix_tokens_saved", 0)
            - warm_stats.get("prefix_tokens_saved", 0),
            "cow_copies": engine_stats.get("cow_copies", 0),
            "tree_nodes": engine_stats.get("tree_nodes", 0),
            "blocks_in_use": engine_stats.get("blocks_in_use", 0),
            "pool_blocks": engine_stats.get("pool_blocks", 0),
        }
        # speculative drafting efficiency of the MEASURED section (the
        # batched lane counts per verify step; the exclusive lane's
        # acceptance happens inside its whole-generation program and is
        # not separately observable here)
        spec_steps = engine_stats.get("spec_steps", 0) \
            - warm_stats.get("spec_steps", 0)
        spec_prop = engine_stats.get("spec_proposed", 0) \
            - warm_stats.get("spec_proposed", 0)
        spec_acc = engine_stats.get("spec_accepted", 0) \
            - warm_stats.get("spec_accepted", 0)
        spec = {
            "draft_k": spec_k,
            "verify_steps": spec_steps,
            "proposed": spec_prop,
            "accepted": spec_acc,
            "acceptance_rate": round(spec_acc / spec_prop, 3)
            if spec_prop else 0.0,
            "mean_accepted_per_step": round(spec_acc / spec_steps, 3)
            if spec_steps else 0.0,
        }
        # per-request phase percentiles of the MEASURED section (ISSUE
        # 12): TTFT/TPOT/queue-wait p50/p99 straight from the recorder,
        # plus the audit block requests_audit.json aggregates
        request_phases = rec.percentiles() if rec is not None else None
        requests_audit = rec.audit_payload() if rec is not None else None
        return {
            "mode": mode or ("batched" if slots > 0 else "single_flight"),
            "slots": slots,
            "request_log": rec is not None,
            "request_phases": request_phases,
            "requests_audit": requests_audit,
            "temperature": temperature,
            "batch_sampling": bool(batch_sampling) and slots > 0,
            "batch_spec": bool(batch_spec) and slots > 0,
            "shared_frac": shared_frac,
            "compile": compile_counts,
            "compile_ledger": compile_ledger,
            "prefix": prefix,
            "spec": spec,
            "requests": len(lat_all),
            "errors": errors[:5],
            "wall_s": round(wall, 3),
            "tokens": tokens_out[0],
            "tokens_per_s": round(tokens_out[0] / max(wall, 1e-9), 1),
            "latency_p50_s": round(_quantile(lat_all, 0.50), 4),
            "latency_p99_s": round(_quantile(lat_all, 0.99), 4),
            "short_p99_s": round(_quantile(lat_short, 0.99), 4),
            "mean_batch_occupancy": round(sum(occ) / len(occ), 2)
            if occ else None,
            # downsampled (step, active-slots) curve: how full the batch
            # stayed over the run, compact enough for the JSON line
            "occupancy_timeline": _downsample(
                engine_stats.get("occupancy_timeline", []), 32),
            "decode_steps": engine_stats.get("steps"),
            "prefill_programs": engine_stats.get("prefill_programs"),
        }
    finally:
        httpd.shutdown()
        lm.close()
        requestlog.set_active(prev_rec)


def check_sampled_equivalence(config, params, template_len: int = 40,
                              tail_len: int = 6) -> bool:
    """Fixed-seed spot check over real HTTP: the batched sampling lane
    and the exclusive lane must emit IDENTICAL tokens — the bench's
    speedup claim is only meaningful if the routing is output-invariant."""
    from k8s_tpu.models.server import LmServer, serve
    from k8s_tpu.util.metrics import Registry

    payload = {"tokens": _shared_prompt(3, 1, template_len, tail_len,
                                        True),
               "max_new_tokens": 8, "temperature": 1.0, "seed": 7}
    outs = []
    for batch_sampling in (True, False):
        lm = LmServer(config=config, params=params, slots=2,
                      queue_limit=8, batch_sampling=batch_sampling,
                      registry=Registry())
        httpd = serve(lm)
        try:
            outs.append(_post("http://%s:%d" % httpd.server_address[:2],
                              payload))
        finally:
            httpd.shutdown()
            lm.close()
    return outs[0] == outs[1]


def check_spec_equivalence(config, params, draft_k: int = 4) -> bool:
    """Fixed-seed spot check over real HTTP: the batched speculative
    lane and the exclusive lane must emit IDENTICAL tokens for greedy
    AND sampled speculation — the spec speedup claim is only meaningful
    if the routing is output-invariant."""
    from k8s_tpu.models.server import LmServer, serve
    from k8s_tpu.util.metrics import Registry

    payloads = [
        {"tokens": _spec_prompt(3, 1), "max_new_tokens": 8,
         "speculative": draft_k},
        {"tokens": _spec_prompt(4, 2), "max_new_tokens": 8,
         "speculative": draft_k, "temperature": 1.0, "seed": 7},
    ]
    outs = []
    for batch_spec in (True, False):
        lm = LmServer(config=config, params=params, slots=2,
                      queue_limit=8, batch_spec=batch_spec,
                      registry=Registry())
        httpd = serve(lm)
        try:
            url = "http://%s:%d" % httpd.server_address[:2]
            outs.append([_post(url, p) for p in payloads])
        finally:
            httpd.shutdown()
            lm.close()
    return outs[0] == outs[1]


def run_bench(concurrency: int = 16, slots: int = 8,
              requests_per_client: int = 4, max_new_short: int = 32,
              max_new_long: int = 64, seed: int = 0,
              sampled: bool = True, shared_frac: float = 0.8,
              spec: bool = True, draft_k: int = 4) -> dict:
    """Single-flight vs continuous batching over the same model/workload
    (the PR-5 greedy phases), plus the round-6 production mix (80%
    shared-prefix traffic at temperature>0, exclusive-lane sampling vs
    the batched sampling lane with prefix reuse), plus the round-9
    speculative phases (exclusive-lane vs batched variable-width
    speculation over structured prompts).  Returns the JSON-able
    comparison dict."""
    config, params = build_model(seed)
    single = run_phase(config, params, slots=0, concurrency=concurrency,
                       requests_per_client=requests_per_client,
                       max_new_short=max_new_short,
                       max_new_long=max_new_long)
    # prefix reuse OFF in the greedy comparison: the slots=0 baseline
    # cannot have a prefix cache, so leaving it on would fold reuse wins
    # into the "continuous batching vs single flight" claim (the warmup
    # even pre-seeds client 0's exact prompts).  The sampled phases
    # below measure reuse explicitly.
    # Recorder overhead pairs (ISSUE 12): the IDENTICAL batched
    # workload with the request recorder off and on — the recorder must
    # pay for itself (within 3%, asserted below) the way the compile
    # ledger's lazy fingerprinting did.  Interleaved best-of-2 per arm:
    # closed-loop tokens/s on a shared CI box swings several percent
    # run-to-run, so a single off/on pair would flake the 3% bound on
    # scheduler noise rather than recorder cost; the max of two
    # interleaved runs per arm compares best-case against best-case.
    # The recorder-ON winner is the headline: it is the shipped
    # configuration.
    greedy_kw = dict(slots=slots, concurrency=concurrency,
                     requests_per_client=requests_per_client,
                     max_new_short=max_new_short,
                     max_new_long=max_new_long, prefix_blocks=0)
    off_runs, on_runs = [], []
    for _ in range(2):
        off_runs.append(run_phase(config, params,
                                  mode="batched_recorder_off",
                                  **greedy_kw))
        on_runs.append(run_phase(config, params, request_log=True,
                                 **greedy_kw))
    batched_off = max(off_runs, key=lambda p: p["tokens_per_s"])
    batched = max(on_runs, key=lambda p: p["tokens_per_s"])
    speedup = batched["tokens_per_s"] / max(single["tokens_per_s"], 1e-9)
    recorder_ratio = batched["tokens_per_s"] \
        / max(batched_off["tokens_per_s"], 1e-9)
    result = {
        "metric": "serve_tokens_per_s",
        "value": batched["tokens_per_s"],
        "unit": "tok/s",
        "concurrency": concurrency,
        "requests_per_client": requests_per_client,
        "max_new_short": max_new_short,
        "max_new_long": max_new_long,
        "single_flight": single,
        "batched": batched,
        "batched_recorder_off": batched_off,
        "recorder_overhead": {
            "on_tokens_per_s": batched["tokens_per_s"],
            "off_tokens_per_s": batched_off["tokens_per_s"],
            "on_runs": [p["tokens_per_s"] for p in on_runs],
            "off_runs": [p["tokens_per_s"] for p in off_runs],
            "ratio": round(recorder_ratio, 4),
        },
        "speedup": round(speedup, 2),
        # iteration-level scheduling headline: short requests behind a
        # long generation (p99) — serialized vs continuous batching
        "short_p99_single_s": single["short_p99_s"],
        "short_p99_batched_s": batched["short_p99_s"],
    }
    if sampled:
        # the production-shaped mix: templated prompts, temperature>0.
        # Baseline = the pre-round-6 engine (sampling exclusive, no
        # prefix reuse); candidate = batched sampling + radix reuse.
        # Load is raised past the greedy phases' (2x the clients): a
        # serialized baseline is load-invariant while the batched lane
        # exists exactly to convert backlog into occupancy.
        sampled_kw = dict(
            slots=slots, concurrency=concurrency * 2,
            requests_per_client=requests_per_client,
            max_new_short=max_new_short, max_new_long=max_new_long,
            temperature=1.0, shared_frac=shared_frac,
            request_log=True)
        exclusive = run_phase(config, params, batch_sampling=False,
                              prefix_blocks=0, mode="sampled_exclusive",
                              **sampled_kw)
        promoted = run_phase(config, params, batch_sampling=True,
                             prefix_blocks=None, mode="sampled_batched",
                             **sampled_kw)
        result["sampled_exclusive"] = exclusive
        result["sampled_batched"] = promoted
        result["sampled_speedup"] = round(
            promoted["tokens_per_s"]
            / max(exclusive["tokens_per_s"], 1e-9), 2)
        result["sampled_shared_frac"] = shared_frac
        result["sampled_equivalence_ok"] = check_sampled_equivalence(
            config, params)
    if spec:
        # the round-9 speculative phases: identical structured-prompt
        # workload, only the lane routing differs.  Baseline = the
        # pre-round-9 engine (speculation single-flight on the exclusive
        # lane); candidate = write-masked variable-width slot lanes.
        # Like the sampled phases, load is raised past the greedy
        # phases' (2x the clients): the serialized baseline is
        # load-invariant while the batched lane converts backlog into
        # occupancy.
        # the spec phases run the hidden-512 variant of the bench model:
        # a draft_k-wide verify has draft_k x the arithmetic intensity
        # of a 1-wide step, and the phase must stay param-bound for the
        # lane comparison to measure the serving mechanism (shared
        # weight streams across slots) — see build_model's docstring.
        # Slots are doubled like the sampled phases double clients:
        # spec slots spend several iterations per emitted-token budget
        # verifying, so the batched lane's natural operating width is
        # wider.  Prefix reuse stays ON for the batched phase and is
        # moot for the exclusive one — exclusive-lane speculation runs
        # whole-generation programs over a private dense cache and
        # ARCHITECTURALLY cannot reuse the pool; flowing spec requests
        # through the paged pool (where templated/grounded traffic
        # attaches its repeated prefixes) is part of the round-9 win
        # being measured.
        # requests are doubled too: the batched lane pays a ramp/drain
        # tail (occupancy builds from 1 and empties at the end) that the
        # load-invariant serialized baseline does not — a longer
        # closed-loop run measures the steady state both lanes actually
        # serve
        spec_config, spec_params = build_model(seed, hidden=512)
        spec_kw = dict(
            slots=slots * 2, concurrency=concurrency * 2,
            requests_per_client=requests_per_client * 2,
            max_new_short=max_new_short, max_new_long=max_new_long,
            spec_k=draft_k, request_log=True,
            spec_prompts=_grounded_spec_prompts(spec_config,
                                                spec_params))
        spec_excl = run_phase(spec_config, spec_params, batch_spec=False,
                              prefix_blocks=0, mode="spec_exclusive",
                              **spec_kw)
        spec_prom = run_phase(spec_config, spec_params, batch_spec=True,
                              prefix_blocks=None, mode="spec_batched",
                              **spec_kw)
        result["spec_exclusive"] = spec_excl
        result["spec_batched"] = spec_prom
        result["spec_speedup"] = round(
            spec_prom["tokens_per_s"]
            / max(spec_excl["tokens_per_s"], 1e-9), 2)
        result["spec_draft_k"] = draft_k
        result["spec_acceptance_rate"] = \
            spec_prom["spec"]["acceptance_rate"]
        result["spec_equivalence_ok"] = check_spec_equivalence(
            spec_config, spec_params, draft_k)
    # Embedded assertions (the bench_churn.json contract, ISSUE 8
    # drive-by: every bench artifact reports failures the same way): a
    # violated invariant attaches a ``failures`` field and raises with
    # the full result on the exception, so the artifact still lands in
    # the non-gating CI tier for whoever debugs the regression.
    # per-phase recorder audits (the requests_audit.json artifact shape
    # bench_operator --requests-audit-out writes, on failure too)
    result["requests_audit"] = {
        phase["mode"]: phase["requests_audit"]
        for phase in (batched, result.get("sampled_batched") or {},
                      result.get("sampled_exclusive") or {},
                      result.get("spec_batched") or {},
                      result.get("spec_exclusive") or {})
        if phase and phase.get("requests_audit") is not None}
    failures: list[str] = []
    for phase in (single, batched, batched_off,
                  result.get("sampled_exclusive") or {},
                  result.get("sampled_batched") or {},
                  result.get("spec_exclusive") or {},
                  result.get("spec_batched") or {}):
        if phase.get("errors"):
            failures.append(
                f"phase {phase.get('mode')}: request errors "
                f"{phase['errors']}")
    # the recorder must pay for itself (ISSUE 12): recorder-ON batched
    # tokens/s within 3% of recorder-OFF on the identical workload
    if recorder_ratio < 0.97:
        failures.append(
            f"request recorder overhead too high: recorder-ON batched "
            f"{batched['tokens_per_s']} tok/s is "
            f"{round((1 - recorder_ratio) * 100, 1)}% below "
            f"recorder-OFF {batched_off['tokens_per_s']} tok/s "
            "(> 3% bound): per-request recording is taxing the decode "
            "loop it observes")
    if sampled and not result["sampled_equivalence_ok"]:
        failures.append(
            "sampled routing not output-invariant: batched sampling lane "
            "and exclusive lane emitted different tokens at a fixed seed")
    if spec:
        if not result["spec_equivalence_ok"]:
            failures.append(
                "speculative routing not output-invariant: batched spec "
                "lane and exclusive lane emitted different tokens at a "
                "fixed seed")
        if result["spec_speedup"] < 1.5:
            failures.append(
                f"spec_batched only {result['spec_speedup']}x "
                "spec_exclusive aggregate tokens/s (< 1.5x bound): the "
                "batched spec lanes are not converting the serialized "
                "exclusive backlog into occupancy")
    # the paged-attention decode step (round 9) must preserve the
    # continuous-batching win: batched greedy no slower than the
    # single-flight baseline on the same machine (the machine-portable
    # form of ">= the PR 6 gather-view numbers"; docs/performance.md
    # carries the absolute before/after)
    if batched["tokens_per_s"] < single["tokens_per_s"]:
        failures.append(
            f"batched greedy {batched['tokens_per_s']} tok/s fell below "
            f"single-flight {single['tokens_per_s']} tok/s: the paged "
            "decode step regressed the continuous-batching win")
    # compile-count contract: prefill bounded by the bucket set, decode
    # programs by the static (fused width x sampling x spec) sets.
    # With the runtime ledger on (K8S_TPU_COMPILE_LEDGER=1) the DECLARED
    # budgets are the contract — every phase's seams must be in budget,
    # exclusive lanes and whole-gen programs included; without it, fall
    # back to the pre-ledger hand-rolled decode-program bound.
    for phase in (single, batched,
                  result.get("sampled_exclusive") or {},
                  result.get("sampled_batched") or {},
                  result.get("spec_exclusive") or {},
                  result.get("spec_batched") or {}):
        ledger_audit = phase.get("compile_ledger") if phase else None
        if ledger_audit is not None and ledger_audit["over_budget"]:
            detail = {s["seam"]: f"{s['programs']}>{s['budget']}"
                      for s in ledger_audit["seams"]
                      if s["over_budget"]}
            failures.append(
                f"phase {phase.get('mode')}: compile seams over budget "
                f"{detail}: the declared program inventory no longer "
                "bounds the compile surface")
    for phase in (batched, result.get("sampled_batched") or {},
                  result.get("spec_batched") or {}):
        if phase and phase.get("compile_ledger") is None \
                and phase["compile"]["decode_programs"] > 10:
            failures.append(
                f"phase {phase.get('mode')}: "
                f"{phase['compile']['decode_programs']} decode programs "
                "(> the static-set bound of 10): compile count is no "
                "longer bounded")
    if failures:
        result["failures"] = failures
        err = RuntimeError("serve bench assertions failed:\n  "
                           + "\n  ".join(failures))
        err.result = result
        raise err
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--concurrency", type=int, default=16,
                   help="closed-loop client threads (>= 2; client 0 "
                   "issues long generations; > slots keeps a backlog so "
                   "slots stay fed through client turnaround)")
    p.add_argument("--slots", type=int, default=8,
                   help="decode slots for the batched phase")
    p.add_argument("--requests", type=int, default=4,
                   help="requests per client per phase")
    p.add_argument("--max-new-short", type=int, default=32)
    p.add_argument("--max-new-long", type=int, default=64,
                   help="the long-client generation length (the head-of-"
                   "line blocker for the serialized baseline)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sampled", type=int, choices=(0, 1), default=1,
                   help="also run the shared-prefix temperature>0 "
                   "phases: exclusive-lane sampling vs the batched "
                   "sampling lane with prefix reuse (default on)")
    p.add_argument("--shared-frac", type=float, default=0.8,
                   help="fraction of sampled-phase requests sharing the "
                   "templated prompt prefix")
    p.add_argument("--spec", type=int, choices=(0, 1), default=1,
                   help="also run the speculative phases: exclusive-lane "
                   "vs batched variable-width speculation over "
                   "structured prompts (acceptance rate + compile "
                   "counts land in the JSON artifact)")
    p.add_argument("--draft-k", type=int, default=4,
                   help="speculative draft chunk width for the spec "
                   "phases")
    p.add_argument("--out", default=None,
                   help="also write the JSON result to this path "
                   "(bench artifact)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)

    def _write(payload: dict) -> None:
        line = json.dumps(payload)
        print(line)
        if args.out:
            import os

            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                f.write(line + "\n")

    try:
        result = run_bench(concurrency=args.concurrency, slots=args.slots,
                           requests_per_client=args.requests,
                           max_new_short=args.max_new_short,
                           max_new_long=args.max_new_long, seed=args.seed,
                           sampled=bool(args.sampled),
                           shared_frac=args.shared_frac,
                           spec=bool(args.spec), draft_k=args.draft_k)
    except RuntimeError as e:
        # artifact written on failure too, ``failures`` field included
        # (the bench_churn.json contract)
        partial = getattr(e, "result", None)
        if partial is not None:
            _write(partial)
        raise
    _write(result)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

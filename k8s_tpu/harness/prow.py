"""Prow CI glue (reference: py/prow.py:81-315).

Writes the gubernator artifact layout — started.json / finished.json /
build-log.txt / junit files / latest_green.json / PR symlinks — through the
pluggable artifact store.  Env contract matches prow's job environment
variables (JOB_NAME, BUILD_NUMBER, PULL_NUMBER, PULL_REFS, PULL_PULL_SHA,
PULL_BASE_SHA, REPO_OWNER).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time

from k8s_tpu.harness import junit
from k8s_tpu.harness.artifacts import LocalArtifactStore, split_uri

log = logging.getLogger(__name__)

# Default repository coordinates (prow.py:29-31).
REPO_OWNER = "kubeflow"
REPO_NAME = "tf-operator-tpu"

# The store bucket that holds CI logs (reference: kubernetes-jenkins on GCS).
LOGS_BUCKET = "ci-logs"
RESULTS_BUCKET = "ci-results"
STORE_SCHEME = "store"


def get_output_dir() -> str:
    """Store URI for this job's output, per the gubernator layout
    (prow.py:36-64): PR jobs under pr-logs/pull/, postsubmits under
    logs/<owner>_<repo>/, periodics under logs/<job>/."""
    job_name = os.getenv("JOB_NAME")
    build = os.getenv("BUILD_NUMBER")
    pull_number = os.getenv("PULL_NUMBER")
    if pull_number:
        return (
            f"{STORE_SCHEME}://{LOGS_BUCKET}/pr-logs/pull/"
            f"{REPO_OWNER}_{REPO_NAME}/{pull_number}/{job_name}/{build}"
        )
    if os.getenv("REPO_OWNER"):
        return (
            f"{STORE_SCHEME}://{LOGS_BUCKET}/logs/"
            f"{REPO_OWNER}_{REPO_NAME}/{job_name}/{build}"
        )
    return f"{STORE_SCHEME}://{LOGS_BUCKET}/logs/{job_name}/{build}"


def get_symlink_output(pull_number: str | None, job_name: str, build_number: str) -> str:
    """PR jobs get a pr-logs/directory symlink file (prow.py:67-78)."""
    if not pull_number:
        return ""
    return (
        f"{STORE_SCHEME}://{LOGS_BUCKET}/pr-logs/directory/"
        f"{job_name}/{build_number}.txt"
    )


def create_started(store, output_dir: str, sha: str) -> str:
    """Write started.json (prow.py:81-116)."""
    started = {
        "timestamp": int(time.time()),
        "repos": {f"{REPO_OWNER}/{REPO_NAME}": sha},
    }
    pull_refs = os.getenv("PULL_REFS", "")
    if pull_refs:
        started["pull"] = pull_refs
    bucket, path = split_uri(output_dir)
    return store.upload_from_string(
        bucket, os.path.join(path, "started.json"), json.dumps(started)
    )


def create_finished(store, output_dir: str, success: bool) -> str:
    """Write finished.json with SUCCESS/FAILURE (prow.py:119-149)."""
    finished = {
        "timestamp": int(time.time()),
        "result": "SUCCESS" if success else "FAILURE",
        "metadata": {},
    }
    bucket, path = split_uri(output_dir)
    return store.upload_from_string(
        bucket, os.path.join(path, "finished.json"), json.dumps(finished)
    )


def create_symlink(store, symlink: str, output: str) -> str:
    """Write the symlink file pointing at the output dir (prow.py:152-167)."""
    bucket, path = split_uri(symlink)
    return store.upload_from_string(bucket, path, output)


def upload_outputs(store, output_dir: str, build_log: str) -> None:
    """Upload the build log as build-log.txt (prow.py:170-180)."""
    bucket, path = split_uri(output_dir)
    if not os.path.exists(build_log):
        log.error("File %s doesn't exist.", build_log)
        return
    store.upload_from_filename(bucket, os.path.join(path, "build-log.txt"), build_log)


def get_commit_from_env() -> str:
    """Presubmits test PULL_PULL_SHA, postsubmits PULL_BASE_SHA
    (prow.py:183-195)."""
    if os.getenv("PULL_NUMBER", ""):
        return os.getenv("PULL_PULL_SHA", "")
    return os.getenv("PULL_BASE_SHA", "")


def create_latest(store, job_name: str, sha: str) -> str:
    """Record the latest passing postsubmit (prow.py:198-215)."""
    data = {"status": "passing", "job": job_name, "sha": sha}
    return store.upload_from_string(
        RESULTS_BUCKET,
        os.path.join(job_name, "latest_green.json"),
        json.dumps(data),
    )


def check_no_errors(store, artifacts_dir: str, junit_files: list[str]) -> bool:
    """All expected junit files exist, none has failures, and no extra junit
    files ran (prow.py:224-262)."""
    bucket, prefix = split_uri(artifacts_dir)
    no_errors = True

    actual_junit = {
        os.path.basename(p)
        for p in store.list(bucket, os.path.join(prefix, "junit"))
    }
    for f in junit_files:
        full = os.path.join(prefix, f)
        log.info("Checking %s", full)
        if not store.exists(bucket, full):
            log.error("Missing %s", full)
            no_errors = False
            continue
        if junit.get_num_failures(store.download_as_string(bucket, full)) > 0:
            log.info("Test failures in %s", full)
            no_errors = False

    extra = actual_junit - set(junit_files)
    if extra:
        log.error("Extra junit files found: %s", ",".join(sorted(extra)))
        no_errors = False
    return no_errors


def finalize_prow_job(store, junit_files: list[str]) -> bool:
    """Determine job status from junit files and write finished.json
    (prow.py:266-279)."""
    output_dir = get_output_dir()
    artifacts_dir = os.path.join(output_dir, "artifacts")
    no_errors = check_no_errors(store, artifacts_dir, junit_files)
    create_finished(store, output_dir, no_errors)
    return no_errors


def create_pr_symlink(store) -> str:
    """The Argo `create-pr-symlink` step (reference workflow:
    test/workflows/components/workflows.libsonnet:307-314 invoking
    prow_artifacts create_pr_symlink): for PR jobs, write the
    pr-logs/directory pointer at the job's output dir."""
    pull_number = os.getenv("PULL_NUMBER")
    symlink = get_symlink_output(
        pull_number, os.getenv("JOB_NAME", ""), os.getenv("BUILD_NUMBER", "")
    )
    if not symlink:
        log.info("not a PR job (no PULL_NUMBER); skipping symlink")
        return ""
    return create_symlink(store, symlink, get_output_dir())


def copy_artifacts(store, artifacts_dir: str) -> int:
    """The Argo `copy-artifacts` step (workflows.libsonnet:333-341):
    upload everything under ``artifacts_dir`` to the job's output dir,
    preserving relative paths.  Returns the file count; a missing
    artifacts dir is an error (a silent 0-file green here would hide the
    real failure until finalize_job reports missing junit files)."""
    if not os.path.isdir(artifacts_dir):
        raise FileNotFoundError(f"artifacts dir does not exist: {artifacts_dir}")
    output_dir = get_output_dir()
    bucket, base = split_uri(output_dir)
    count = 0
    for root, _, files in os.walk(artifacts_dir):
        for fname in files:
            local = os.path.join(root, fname)
            rel = os.path.relpath(local, artifacts_dir)
            store.upload_from_filename(bucket, os.path.join(base, rel), local)
            count += 1
    log.info("copied %d artifact files to %s", count, output_dir)
    return count


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    parser = argparse.ArgumentParser(description="Steps related to prow.")
    parser.add_argument(
        "--artifacts_root",
        default=os.getenv("ARTIFACTS_ROOT", "/tmp/k8s_tpu_artifacts"),
        help="Local artifact store root.",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    fin = sub.add_parser("finalize_job", help="Finalize the prow job.")
    fin.add_argument(
        "--junit_files",
        default="",
        help="Comma separated list of expected junit file names.",
    )
    symlink = sub.add_parser(
        "create_pr_symlink", help="Write the PR directory pointer.")
    copy = sub.add_parser("copy_artifacts", help="Upload the artifacts dir.")
    copy.add_argument("--artifacts_dir", required=True)
    # accept --artifacts_root after the subcommand too (the historical
    # finalize_job flag position); SUPPRESS keeps the top-level value
    # unless the subcommand explicitly overrides it
    for p in (fin, symlink, copy):
        p.add_argument("--artifacts_root", default=argparse.SUPPRESS,
                       help=argparse.SUPPRESS)
    args = parser.parse_args(argv)
    store = LocalArtifactStore(args.artifacts_root)
    if args.command == "create_pr_symlink":
        create_pr_symlink(store)
        return 0
    if args.command == "copy_artifacts":
        copy_artifacts(store, args.artifacts_dir)
        return 0
    ok = finalize_prow_job(store, [f for f in args.junit_files.split(",") if f])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

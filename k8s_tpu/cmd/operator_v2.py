"""v2 operator binary (reference: cmd/tf-operator.v2/).

Flags mirror cmd/tf-operator.v2/app/options/options.go:37-49; run flow
mirrors app.Run (server.go:57-154): clients → unstructured informer wiring →
leader election → controller.Run.
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import sys

from k8s_tpu import version
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.util.leader_election import LeaderElectionConfig, LeaderElector
from k8s_tpu.util.signals import merge_stop_events, setup_signal_handler
from k8s_tpu.util.util import get_namespace

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpu-operator-v2")
    p.add_argument("--master", default="", help="apiserver URL override (options.go:44)")
    p.add_argument("--kubeconfig", default=os.environ.get("KUBECONFIG", ""))
    p.add_argument("--threadiness", type=int, default=2, help="options.go:42")
    p.add_argument("--namespace", default="")
    p.add_argument("--enable-gang-scheduling", action="store_true", default=True)
    p.add_argument("--no-gang-scheduling", dest="enable_gang_scheduling",
                   action="store_false")
    p.add_argument("--json-log-format", action="store_true")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve /metrics + /healthz on this port (0 = off, "
                   "matching the reference, which exposes no endpoint)")
    p.add_argument("--metrics-host", default="0.0.0.0",
                   help="bind address for the metrics endpoint. The "
                   "endpoints are UNAUTHENTICATED: the default binds all "
                   "interfaces because in-pod scrapers must reach them; "
                   "pass 127.0.0.1 to restrict to loopback (the library "
                   "default outside this binary)")
    p.add_argument("--cluster-chips", type=int, default=None,
                   help="total TPU chips the gang-admission scheduler may "
                   "reserve (ISSUE 4).  Default: K8S_TPU_CLUSTER_CHIPS, "
                   "else derived from node allocatable "
                   "cloud-tpus.google.com/* resources, else unlimited "
                   "(admission disabled); 0 = explicitly unlimited")
    p.add_argument("--version", action="store_true")
    return p


def make_backend(opts):
    from k8s_tpu.client.rest import (
        ClusterConfig,
        RestClient,
        get_cluster_config,
        kubeconfig_config,
    )

    if opts.master:
        return RestClient(ClusterConfig(host=opts.master))
    if opts.kubeconfig:
        return RestClient(kubeconfig_config(opts.kubeconfig))
    return RestClient(get_cluster_config())


def run(opts, backend=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format='{"level":"%(levelname)s","msg":"%(message)s","time":"%(asctime)s"}'
        if opts.json_log_format
        else "%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    from k8s_tpu.controller_v2.controller import TFJobController

    clientset = Clientset(backend if backend is not None else make_backend(opts))
    controller = TFJobController(
        clientset, enable_gang_scheduling=opts.enable_gang_scheduling,
        cluster_chips=getattr(opts, "cluster_chips", None),
    )
    stop = setup_signal_handler()

    from k8s_tpu.util.metrics_server import maybe_start

    metrics_server = maybe_start(getattr(opts, "metrics_port", 0),
                                host=getattr(opts, "metrics_host", "0.0.0.0"),
                                health_fn=controller.healthy)

    namespace = opts.namespace or get_namespace()
    elector = LeaderElector(
        clientset,
        LeaderElectionConfig(
            namespace=namespace,
            name="tf-operator-v2",
            identity=f"{socket.gethostname()}-{os.getpid()}",
        ),
    )

    def on_started_leading(stop_work):
        controller.run(
            opts.threadiness, stop_event=merge_stop_events(stop, stop_work)
        )

    def on_stopped_leading():
        log.error("leader election lost")
        os._exit(1)

    try:
        elector.run_or_die(on_started_leading, on_stopped_leading)
    finally:
        if metrics_server is not None:
            metrics_server.stop()
    return 0


def main() -> int:
    opts = build_parser().parse_args()
    if opts.version:
        version.print_version("tpu-operator-v2")
        return 0
    return run(opts)


if __name__ == "__main__":
    sys.exit(main())

"""TFJob load generator (reference: hack/genjob/genjob.go:30-120).

Fabricates N TFJobs for scale/scheduler testing — worker-only jobs by
default, master+GPU jobs with ``--use-gpu``, TPU gang jobs with ``--use-tpu``
(the rebuild's own axis), all optionally pinned to a custom scheduler.  With
``--dump`` the manifests go to stdout for kubectl; otherwise they're created
through the clientset against the configured cluster.
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

import yaml

log = logging.getLogger(__name__)


V5E_CHIPS_PER_HOST = 4
V5E_MAX_HOSTS = 64  # v5litepod-256 (16x16) is the largest v5e slice

# The serving container's HTTP port (models/server.py --port): the
# command, containerPort, readiness probe, and the DEFAULT fleet scrape
# target all derive from this one constant — /metrics lives on the same
# server, so advertising any other scrape port means a sidecar exporter.
SERVE_HTTP_PORT = 8000
# The multi-host serving gang's plan-bus port (ISSUE 14): a FIXED port,
# stamped as K8S_TPU_SERVE_PLAN_PORT on every gang pod — workers dial
# the chief pod's hostname on it, so an ephemeral (0) port would be
# undiscoverable across pods and the gang could never rendezvous.
SERVE_PLAN_PORT = 8471
# The decode tier's KV block-transfer listener (ISSUE 15): fixed for
# the same reason — prefill pods (via the router's kv_dest) dial decode
# pods on it.  Must equal models/kvxfer.DEFAULT_PORT (pinned by test).
KVXFER_PORT = 8472


def v5e_slice_for_hosts(num_hosts: int) -> tuple[str, str]:
    """(acceleratorType, topology) for a v5e slice of ``num_hosts`` hosts
    (4 chips/host).  v5e topologies are XxY chip grids with power-of-two
    sides, so num_hosts must be a power of two (1 -> 2x2 single host,
    4 -> 4x4, 16 -> 8x8, ...), capped at the real product's 256-chip pod
    (scale past that is multislice, not a bigger slice)."""
    if num_hosts < 1 or num_hosts & (num_hosts - 1):
        raise ValueError(
            f"v5e slices need a power-of-two host count, got {num_hosts}"
        )
    if num_hosts > V5E_MAX_HOSTS:
        raise ValueError(
            f"v5e slices top out at {V5E_MAX_HOSTS} hosts (v5litepod-256); "
            f"got {num_hosts} — use multiple slices (multislice) instead"
        )
    chips = num_hosts * V5E_CHIPS_PER_HOST
    x = 1
    while x * x < chips:
        x *= 2
    if x * x > chips:
        x //= 2
    y = chips // x
    return f"v5litepod-{chips}", f"{x}x{y}"


def serve_tfjob_template(
    job_name: str,
    namespace: str = "default",
    train_dir: str = "/checkpoints/train-lm",
    scheduler_name: str = "default",
    serve_slots: int = 8,
    serve_queue: int = 64,
    serve_prefix_blocks: int | None = None,
    serve_batch_sampling: bool = True,
    serve_batch_spec: bool = True,
    serve_request_log: bool = True,
    serve_request_log_ring: int | None = None,
    serve_spill_mb: int | None = None,
    kvxfer_dedup: bool | None = None,
    priority: int | None = None,
    queue: str | None = None,
    fleet_scrape_port: int | None = SERVE_HTTP_PORT,
    fleet_interval_s: float | None = None,
    autoscale_min: int | None = None,
    autoscale_max: int | None = None,
    serve_mesh: int | None = None,
    serve_weight: float | None = None,
) -> dict:
    """A resident serving TFJob (the examples/tf_job_serve_http.yaml
    shape) with the engine knobs surfaced as env: decode slots and
    admission queue bound, plus the round-6 shared-prefix KV pool
    retention (``K8S_TPU_SERVE_PREFIX_BLOCKS``; omit for auto, 0
    disables reuse) and the lane-routing knobs — batched sampling
    (``K8S_TPU_SERVE_BATCH_SAMPLING``) and round-9 batched speculative
    decoding (``K8S_TPU_SERVE_BATCH_SPEC``).

    ISSUE 8: generated serving jobs are **fleet-discoverable by
    default** — the pod template carries the
    ``kubeflow.org/fleet-scrape-port`` annotation and the
    ``K8S_TPU_FLEET_SCRAPE_PORT`` env (both pointing at the server's
    HTTP port, where ``/metrics`` lives), so the operator's fleet
    telemetry plane scrapes them with zero extra configuration.
    ``fleet_scrape_port=None`` opts the job out.  The default is the
    server's own HTTP port (``SERVE_HTTP_PORT`` — /metrics lives on the
    same listener); a DIFFERENT value means a sidecar exporter serves
    /metrics there, since the generated command pins the server to
    ``SERVE_HTTP_PORT`` — there is no listener on an arbitrary port.
    ``fleet_interval_s`` additionally surfaces the operator-side
    ``K8S_TPU_FLEET_INTERVAL_S`` knob on the pod for humans reading
    the manifest (the interval is an operator setting — the env on a
    serving pod is documentation, the annotation is the contract).

    ISSUE 12: generated serving jobs record **per-request timelines by
    default** — ``K8S_TPU_REQUEST_LOG=1`` activates the request
    lifecycle recorder (``/debug/requests`` + ``/debug/engine`` on the
    serving port), ``serve_request_log_ring`` pins the finished-
    timeline ring bound (``K8S_TPU_REQUEST_LOG_RING``; omit for the
    512 default), and ``serve_request_log=False`` opts out.

    ISSUE 17: ``serve_spill_mb`` stamps ``K8S_TPU_SERVE_SPILL_MB`` —
    the host-RAM KV spill tier's budget (0/omitted = off).  The spill
    buffers live in POD memory, on top of params and the device pool's
    host shadow: size ``resources.limits.memory`` with at least that
    headroom or the kubelet OOM-kills the pod at exactly the moment
    the tier fills.  ``kvxfer_dedup`` stamps
    ``K8S_TPU_KVXFER_DEDUP`` (the migration block-dedup handshake;
    omit for the server's default, which is ON).

    ISSUE 13: ``autoscale_min``/``autoscale_max`` (both or neither)
    stamp the ``spec.autoscale`` bounds the operator's metric-driven
    gang autoscaler scales inside (``K8S_TPU_AUTOSCALE`` gates the loop
    itself); the Worker replica count starts at ``autoscale_min``.

    ISSUE 14: ``serve_mesh=N`` makes the job a **multi-host
    tensor-parallel serving gang**: N Worker replicas all running the
    same server binary (``K8S_TPU_SERVE_MESH=N``), rendezvousing
    through the operator's ordinary gang env contract — replica 0
    serves HTTP as the chief, the rest replay its batch plan
    (docs/serving.md "Multi-host serving").  ``serve_weight`` stamps
    the ``kubeflow.org/fleet-serve-weight`` annotation so the router's
    weighted hash ring gives the pod keyspace proportional to its
    capacity (a tp=4 gang next to single-chip pods declares 4.0)."""
    env = [
        {"name": "K8S_TPU_SERVE_SLOTS", "value": str(serve_slots)},
        {"name": "K8S_TPU_SERVE_QUEUE", "value": str(serve_queue)},
        {"name": "K8S_TPU_SERVE_BATCH_SAMPLING",
         "value": "1" if serve_batch_sampling else "0"},
        {"name": "K8S_TPU_SERVE_BATCH_SPEC",
         "value": "1" if serve_batch_spec else "0"},
        {"name": "K8S_TPU_REQUEST_LOG",
         "value": "1" if serve_request_log else "0"},
    ]
    if serve_prefix_blocks is not None:
        env.append({"name": "K8S_TPU_SERVE_PREFIX_BLOCKS",
                    "value": str(serve_prefix_blocks)})
    if serve_request_log_ring is not None:
        env.append({"name": "K8S_TPU_REQUEST_LOG_RING",
                    "value": str(serve_request_log_ring)})
    if serve_spill_mb is not None:
        if serve_spill_mb < 0:
            raise ValueError(
                f"serve_spill_mb must be >= 0, got {serve_spill_mb}")
        env.append({"name": "K8S_TPU_SERVE_SPILL_MB",
                    "value": str(serve_spill_mb)})
    if kvxfer_dedup is not None:
        env.append({"name": "K8S_TPU_KVXFER_DEDUP",
                    "value": "1" if kvxfer_dedup else "0"})
    if serve_mesh is not None:
        if serve_mesh < 1:
            raise ValueError(f"serve_mesh must be >= 1, got {serve_mesh}")
        if autoscale_min is not None:
            raise ValueError(
                "serve_mesh and autoscale are mutually exclusive: a "
                "tensor-parallel gang's replica count IS its mesh shape "
                "(scale serving capacity by adding jobs behind the "
                "router, not replicas to the gang)")
        env.append({"name": "K8S_TPU_SERVE_MESH",
                    "value": str(serve_mesh)})
        env.append({"name": "K8S_TPU_SERVE_PLAN_PORT",
                    "value": str(SERVE_PLAN_PORT)})
    if fleet_scrape_port is not None:
        env.append({"name": "K8S_TPU_FLEET_SCRAPE_PORT",
                    "value": str(fleet_scrape_port)})
        if fleet_interval_s is not None:
            env.append({"name": "K8S_TPU_FLEET_INTERVAL_S",
                        "value": str(fleet_interval_s)})
    template_meta: dict = {}
    annotations: dict = {}
    if fleet_scrape_port is not None:
        annotations["kubeflow.org/fleet-scrape-port"] = \
            str(fleet_scrape_port)
    if serve_weight is not None:
        if serve_weight <= 0:
            raise ValueError(
                f"serve_weight must be > 0, got {serve_weight}")
        annotations["kubeflow.org/fleet-serve-weight"] = str(serve_weight)
    if annotations:
        template_meta["annotations"] = annotations
    if (autoscale_min is None) != (autoscale_max is None):
        raise ValueError("give both autoscale_min and autoscale_max "
                         "(or neither)")
    job = {
        "apiVersion": "kubeflow.org/v1alpha2",
        "kind": "TFJob",
        "metadata": {"name": job_name, "namespace": namespace},
        "spec": {
            "tfReplicaSpecs": {
                "Worker": {
                    "replicas": (serve_mesh if serve_mesh is not None
                                 else autoscale_min
                                 if autoscale_min is not None else 1),
                    "restartPolicy": "OnFailure",
                    "template": {
                        **({"metadata": template_meta}
                           if template_meta else {}),
                        "spec": {
                            "schedulerName": scheduler_name,
                            "containers": [
                                {
                                    "name": "tensorflow",
                                    "image": "k8s-tpu/train-lm:latest",
                                    "command": [
                                        "python", "-m",
                                        "k8s_tpu.models.server",
                                        f"--train_dir={train_dir}",
                                        "--host=0.0.0.0",
                                        f"--port={SERVE_HTTP_PORT}",
                                    ],
                                    "env": env,
                                    "ports": [{"containerPort":
                                               SERVE_HTTP_PORT,
                                               "name": "http"}],
                                    "readinessProbe": {
                                        "httpGet": {"path": "/healthz",
                                                    "port":
                                                    SERVE_HTTP_PORT}
                                    },
                                    # match the example manifest: a TPU
                                    # + memory request (the block pool
                                    # lives in pod memory limits) and
                                    # the checkpoint volume the
                                    # --train_dir path loads from
                                    "resources": {
                                        "limits": {
                                            "google.com/tpu": 4,
                                            "memory": "16Gi",
                                        }
                                    },
                                    "volumeMounts": [
                                        {"name": "checkpoints",
                                         "mountPath": "/checkpoints"}
                                    ],
                                }
                            ],
                            "volumes": [
                                {"name": "checkpoints",
                                 "persistentVolumeClaim": {
                                     "claimName": "train-lm-checkpoints"
                                 }}
                            ],
                        }
                    },
                }
            }
        },
    }
    if priority is not None:
        job["spec"]["priority"] = priority
    if queue is not None:
        job["spec"]["queue"] = queue
    if autoscale_min is not None:
        job["spec"]["autoscale"] = {
            "minReplicas": autoscale_min,
            "maxReplicas": autoscale_max,
            "replicaType": "Worker",
        }
    return job


def _serve_replica_spec(replicas: int, env: list, annotations: dict,
                        scheduler_name: str, train_dir: str,
                        restart_policy: str = "OnFailure") -> dict:
    """One serving replica spec (the serve template's pod shape) with
    the given env/annotations — shared by the Prefill and Decode tiers
    of a disaggregated job."""
    template: dict = {
        "spec": {
            "schedulerName": scheduler_name,
            "containers": [
                {
                    "name": "tensorflow",
                    "image": "k8s-tpu/train-lm:latest",
                    "command": [
                        "python", "-m", "k8s_tpu.models.server",
                        f"--train_dir={train_dir}",
                        "--host=0.0.0.0",
                        f"--port={SERVE_HTTP_PORT}",
                    ],
                    "env": env,
                    "ports": [{"containerPort": SERVE_HTTP_PORT,
                               "name": "http"}],
                    "readinessProbe": {
                        "httpGet": {"path": "/healthz",
                                    "port": SERVE_HTTP_PORT}
                    },
                    # the scheduler's TPU resource prefix, so each
                    # tier's chip demand prices SEPARATELY through the
                    # ordinary per-role walk (chips_for_tfjob) — a
                    # 1-prefill/2-decode job reserves 3 hosts' chips
                    "resources": {
                        "limits": {
                            "cloud-tpus.google.com/v5e":
                                V5E_CHIPS_PER_HOST,
                            "memory": "16Gi",
                        }
                    },
                    "volumeMounts": [
                        {"name": "checkpoints",
                         "mountPath": "/checkpoints"}
                    ],
                }
            ],
            "volumes": [
                {"name": "checkpoints",
                 "persistentVolumeClaim": {
                     "claimName": "train-lm-checkpoints"
                 }}
            ],
        }
    }
    if annotations:
        template["metadata"] = {"annotations": dict(annotations)}
    return {
        "replicas": replicas,
        "restartPolicy": restart_policy,
        "template": template,
    }


def disagg_serve_tfjob_template(
    job_name: str,
    namespace: str = "default",
    train_dir: str = "/checkpoints/train-lm",
    scheduler_name: str = "default",
    prefill_replicas: int = 1,
    decode_replicas: int = 2,
    serve_slots: int = 8,
    serve_queue: int = 64,
    serve_prefix_blocks: int | None = None,
    serve_batch_sampling: bool = True,
    serve_batch_spec: bool = True,
    serve_request_log: bool = True,
    serve_request_log_ring: int | None = None,
    serve_spill_mb: int | None = None,
    kvxfer_dedup: bool | None = None,
    priority: int | None = None,
    queue: str | None = None,
    fleet_scrape_port: int | None = SERVE_HTTP_PORT,
    fleet_interval_s: float | None = None,
    kvxfer_port: int = KVXFER_PORT,
    kvxfer_int8: bool = False,
) -> dict:
    """A DISAGGREGATED serving TFJob (ISSUE 15): heterogeneous
    ``Prefill`` and ``Decode`` replica tiers of the same artifact,
    connected by the KV block-transfer plane.

    - **Prefill** pods run ``K8S_TPU_SERVE_ROLE=prefill``: they serve
      the router's phase-split long prompts, chunk-prefill, emit the
      first token, and stream the finished block chain to the decode
      pod the router chose (``kv_dest`` in the request) — no decode
      slot is ever held.  ``kvxfer_int8`` stamps
      ``K8S_TPU_KVXFER_INT8=1`` here (quantization happens on the
      SENDING side; int8 pools ignore it).
    - **Decode** pods run ``K8S_TPU_SERVE_ROLE=decode`` and listen on
      ``K8S_TPU_KVXFER_PORT``: they seat migrated requests directly
      from imported blocks and serve every short prompt locally.

    ISSUE 17 stamps both tiers: ``serve_spill_mb`` sets
    ``K8S_TPU_SERVE_SPILL_MB`` (the host-RAM KV spill tier budget;
    prefill pods spill their prefix tree too — size each tier's
    ``resources.limits.memory`` with that much headroom), and
    ``kvxfer_dedup`` sets ``K8S_TPU_KVXFER_DEDUP`` — the prefill
    sender's block-dedup offer AND the decode receiver's index seam
    (omit for the default, ON).

    Each tier's pod template carries ``kubeflow.org/serve-role`` (and
    the decode tier ``kubeflow.org/kvxfer-port``), so fleet discovery
    hands a role-aware backend set to the router, whose
    ``K8S_TPU_ROUTER_PHASE_TOKENS`` knob does the traffic split.  The
    capacity scheduler prices each tier's chips separately through the
    ordinary per-role demand walk (``chips_for_tfjob``)."""
    if prefill_replicas < 1 or decode_replicas < 1:
        raise ValueError(
            "a disaggregated job needs >= 1 replica per tier "
            f"(got prefill={prefill_replicas}, decode={decode_replicas})")
    base_env = [
        {"name": "K8S_TPU_SERVE_SLOTS", "value": str(serve_slots)},
        {"name": "K8S_TPU_SERVE_QUEUE", "value": str(serve_queue)},
        {"name": "K8S_TPU_SERVE_BATCH_SAMPLING",
         "value": "1" if serve_batch_sampling else "0"},
        {"name": "K8S_TPU_SERVE_BATCH_SPEC",
         "value": "1" if serve_batch_spec else "0"},
        {"name": "K8S_TPU_REQUEST_LOG",
         "value": "1" if serve_request_log else "0"},
    ]
    if serve_prefix_blocks is not None:
        base_env.append({"name": "K8S_TPU_SERVE_PREFIX_BLOCKS",
                         "value": str(serve_prefix_blocks)})
    if serve_request_log_ring is not None:
        base_env.append({"name": "K8S_TPU_REQUEST_LOG_RING",
                         "value": str(serve_request_log_ring)})
    if serve_spill_mb is not None:
        if serve_spill_mb < 0:
            raise ValueError(
                f"serve_spill_mb must be >= 0, got {serve_spill_mb}")
        base_env.append({"name": "K8S_TPU_SERVE_SPILL_MB",
                         "value": str(serve_spill_mb)})
    if kvxfer_dedup is not None:
        base_env.append({"name": "K8S_TPU_KVXFER_DEDUP",
                         "value": "1" if kvxfer_dedup else "0"})
    if fleet_scrape_port is not None:
        base_env.append({"name": "K8S_TPU_FLEET_SCRAPE_PORT",
                         "value": str(fleet_scrape_port)})
        if fleet_interval_s is not None:
            base_env.append({"name": "K8S_TPU_FLEET_INTERVAL_S",
                             "value": str(fleet_interval_s)})
    base_annotations: dict = {}
    if fleet_scrape_port is not None:
        base_annotations["kubeflow.org/fleet-scrape-port"] = \
            str(fleet_scrape_port)

    # per-item copies so the dumped YAML carries no cross-tier anchors
    prefill_env = [dict(e) for e in base_env] + [
        {"name": "K8S_TPU_SERVE_ROLE", "value": "prefill"}]
    if kvxfer_int8:
        prefill_env.append({"name": "K8S_TPU_KVXFER_INT8", "value": "1"})
    decode_env = [dict(e) for e in base_env] + [
        {"name": "K8S_TPU_SERVE_ROLE", "value": "decode"},
        {"name": "K8S_TPU_KVXFER_PORT", "value": str(kvxfer_port)},
    ]
    job = {
        "apiVersion": "kubeflow.org/v1alpha2",
        "kind": "TFJob",
        "metadata": {"name": job_name, "namespace": namespace},
        "spec": {
            "tfReplicaSpecs": {
                "Prefill": _serve_replica_spec(
                    prefill_replicas, prefill_env,
                    {**base_annotations,
                     "kubeflow.org/serve-role": "prefill"},
                    scheduler_name, train_dir),
                "Decode": _serve_replica_spec(
                    decode_replicas, decode_env,
                    {**base_annotations,
                     "kubeflow.org/serve-role": "decode",
                     "kubeflow.org/kvxfer-port": str(kvxfer_port)},
                    scheduler_name, train_dir),
            }
        },
    }
    if priority is not None:
        job["spec"]["priority"] = priority
    if queue is not None:
        job["spec"]["queue"] = queue
    return job


ROUTER_HTTP_PORT = 8080


def router_companion_template(
    job_name: str,
    namespace: str = "default",
    router_port: int = ROUTER_HTTP_PORT,
    policy: str = "affine",
    block_size: int | None = None,
    affinity_blocks: int | None = None,
    retry_budget: int | None = None,
    phase_split_tokens: int | None = None,
) -> dict:
    """The front-door companion Pod for one serving TFJob (ISSUE 13):
    ``python -m k8s_tpu.cmd.router --job <ns>/<name>`` discovering the
    job's pods from its own informer cache and proxying /v1/generate
    with prefix-affine placement.  One router per JOB (it owns the
    consistent-hash ring), not a per-pod sidecar; exposing it behind a
    Service/LB is a deployment decision left to the chart."""
    env = [{"name": "K8S_TPU_ROUTER_POLICY", "value": policy}]
    if block_size is not None:
        env.append({"name": "K8S_TPU_ROUTER_BLOCK_SIZE",
                    "value": str(block_size)})
    if affinity_blocks is not None:
        env.append({"name": "K8S_TPU_ROUTER_AFFINITY_BLOCKS",
                    "value": str(affinity_blocks)})
    if retry_budget is not None:
        env.append({"name": "K8S_TPU_ROUTER_RETRY_BUDGET",
                    "value": str(retry_budget)})
    if phase_split_tokens is not None:
        # disaggregated phase split (ISSUE 15): prompts at/above this
        # token count route to the Prefill tier, then follow their
        # blocks to a Decode pod
        env.append({"name": "K8S_TPU_ROUTER_PHASE_TOKENS",
                    "value": str(phase_split_tokens)})
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{job_name}-router",
            "namespace": namespace,
            "labels": {"app": "tpu-serve-router",
                       "tf_job_name": job_name},
        },
        "spec": {
            "containers": [
                {
                    "name": "router",
                    "image": "k8s-tpu/train-lm:latest",
                    "command": [
                        "python", "-m", "k8s_tpu.cmd.router",
                        f"--job={namespace}/{job_name}",
                        "--host=0.0.0.0",
                        f"--port={router_port}",
                        f"--policy={policy}",
                    ],
                    "env": env,
                    "ports": [{"containerPort": router_port,
                               "name": "http"}],
                    "readinessProbe": {
                        "httpGet": {"path": "/healthz",
                                    "port": router_port}
                    },
                    # drain budget: SIGTERM triggers the clean drain
                    # (503 new, finish in-flight); the grace period must
                    # outlive the longest generation
                }
            ],
            "terminationGracePeriodSeconds": 60,
        },
    }


def tfjob_template(
    job_name: str,
    namespace: str = "default",
    gpu: bool = False,
    tpu: bool = False,
    scheduler_name: str = "default",
    tpu_replicas: int = 4,
    priority: int | None = None,
    queue: str | None = None,
) -> dict:
    """One synthetic job (genjob.go:46-91): 1 WORKER, or 1 MASTER+GPU, or a
    TPU gang of ``tpu_replicas`` hosts.  ``priority``/``queue`` set the
    v1alpha2 gang-admission fields so generated manifests can exercise the
    capacity scheduler (ISSUE 4)."""
    if tpu:
        accel, topology = v5e_slice_for_hosts(tpu_replicas)
        job = {
            "apiVersion": "kubeflow.org/v1alpha2",
            "kind": "TFJob",
            "metadata": {"name": job_name, "namespace": namespace},
            "spec": {
                "tpu": {"acceleratorType": accel, "topology": topology},
                "tfReplicaSpecs": {
                    "TPU": {
                        "replicas": tpu_replicas,
                        "restartPolicy": "ExitCode",
                        "template": {
                            "spec": {
                                "schedulerName": scheduler_name,
                                "containers": [
                                    {
                                        "name": "tensorflow",
                                        "image": "k8s-tpu/smoke:latest",
                                        "resources": {
                                            "limits": {
                                                "cloud-tpus.google.com/v5e":
                                                    V5E_CHIPS_PER_HOST
                                            }
                                        },
                                    }
                                ],
                            }
                        },
                    }
                },
            },
        }
        if priority is not None:
            job["spec"]["priority"] = priority
        if queue is not None:
            job["spec"]["queue"] = queue
        return job
    replica = {
        "replicas": 1,
        "tfReplicaType": "MASTER" if gpu else "WORKER",
        "template": {
            "spec": {
                "schedulerName": scheduler_name,
                "containers": [
                    {
                        "name": "tensorflow",
                        "image": "k8s-tpu/smoke-gpu:latest" if gpu else "k8s-tpu/smoke:latest",
                    }
                ],
                "restartPolicy": "OnFailure",
            }
        },
    }
    if gpu:
        replica["template"]["spec"]["containers"][0]["resources"] = {
            "limits": {"nvidia.com/gpu": 1}
        }
    job = {
        "apiVersion": "kubeflow.org/v1alpha1",
        "kind": "TFJob",
        "metadata": {"name": job_name, "namespace": namespace},
        "spec": {"replicaSpecs": [replica], "schedulerName": scheduler_name},
    }
    # genjob.go:83-88 sets the chief only for GPU (MASTER) jobs; a worker-only
    # job there fails the operator's chief validation.  SPMD makes worker-0
    # the natural chief, so declare it and keep every generated job valid.
    job["spec"]["terminationPolicy"] = {
        "chief": {"replicaName": "MASTER" if gpu else "WORKER"}
    }
    # v1alpha1 has no scheduling fields; the keys still travel in the
    # manifest (ignored by the v1 operator) so one flag works for both
    # generations, but only v1alpha2 jobs are actually arbitrated.
    if priority is not None:
        job["spec"]["priority"] = priority
    if queue is not None:
        job["spec"]["queue"] = queue
    return job


def generate(
    n: int,
    namespace: str = "default",
    gpu: bool = False,
    tpu: bool = False,
    scheduler_name: str = "default",
    timestamp: int | None = None,
    priority: int | None = None,
    queue: str | None = None,
    serve: bool = False,
    serve_slots: int = 8,
    serve_queue: int = 64,
    serve_prefix_blocks: int | None = None,
    serve_batch_sampling: bool = True,
    serve_batch_spec: bool = True,
    serve_request_log: bool = True,
    serve_request_log_ring: int | None = None,
    serve_spill_mb: int | None = None,
    kvxfer_dedup: bool | None = None,
    fleet_scrape_port: int | None = 8000,
    fleet_interval_s: float | None = None,
    router: bool = False,
    router_port: int = ROUTER_HTTP_PORT,
    router_policy: str = "affine",
    router_block_size: int | None = None,
    router_affinity_blocks: int | None = None,
    router_retry_budget: int | None = None,
    autoscale_min: int | None = None,
    autoscale_max: int | None = None,
    serve_mesh: int | None = None,
    serve_weight: float | None = None,
    disagg: bool = False,
    disagg_prefill: int = 1,
    disagg_decode: int = 2,
    disagg_phase_tokens: int = 64,
    kvxfer_port: int = KVXFER_PORT,
    kvxfer_int8: bool = False,
) -> list[dict]:
    """N uniquely-named jobs, ``tfjob-<ts>-<i>`` (genjob.go:111-114).
    ``router=True`` (requires ``serve``) additionally emits each job's
    front-door companion Pod right after its TFJob document;
    ``disagg=True`` (requires ``serve``) emits the two-tier
    Prefill/Decode job instead of the single-role Worker job, with the
    router companion carrying the phase-split threshold."""
    ts = timestamp if timestamp is not None else time.time_ns() % 10**9
    if router and not serve:
        raise ValueError("--router requires --serve (the front door "
                         "proxies serving jobs)")
    if disagg and not serve:
        raise ValueError("--disagg requires --serve (only serving jobs "
                         "split into prefill/decode tiers)")
    if disagg and serve_mesh is not None:
        raise ValueError(
            "--disagg and --serve-mesh are mutually exclusive for now: "
            "a tensor-parallel gang has no single-host pool to export "
            "(disaggregate ACROSS gangs once per-tier meshes land)")
    if disagg and autoscale_min is not None:
        raise ValueError(
            "--disagg and --autoscale-* are mutually exclusive for "
            "now: spec.autoscale targets ONE replica type; per-tier "
            "autoscaling is a follow-up")
    if (autoscale_min is not None or autoscale_max is not None) \
            and not serve:
        # silently dropping the bounds would leave the user believing
        # the job is autoscalable when the spec never carried them
        raise ValueError("--autoscale-min/--autoscale-max require "
                         "--serve (only serving jobs carry "
                         "spec.autoscale)")
    if (serve_mesh is not None or serve_weight is not None) and not serve:
        # same silent-drop hazard: a training job carries neither the
        # gang env nor the weight annotation
        raise ValueError("--serve-mesh/--serve-weight require --serve "
                         "(only serving jobs form tensor-parallel gangs "
                         "or join the router's weighted ring)")
    if serve:
        out: list[dict] = []
        for i in range(n):
            name = f"tfjob-{ts}-{i}"
            if disagg:
                out.append(disagg_serve_tfjob_template(
                    name, namespace,
                    scheduler_name=scheduler_name,
                    prefill_replicas=disagg_prefill,
                    decode_replicas=disagg_decode,
                    serve_slots=serve_slots, serve_queue=serve_queue,
                    serve_prefix_blocks=serve_prefix_blocks,
                    serve_batch_sampling=serve_batch_sampling,
                    serve_batch_spec=serve_batch_spec,
                    serve_request_log=serve_request_log,
                    serve_request_log_ring=serve_request_log_ring,
                    serve_spill_mb=serve_spill_mb,
                    kvxfer_dedup=kvxfer_dedup,
                    priority=priority, queue=queue,
                    fleet_scrape_port=fleet_scrape_port,
                    fleet_interval_s=fleet_interval_s,
                    kvxfer_port=kvxfer_port,
                    kvxfer_int8=kvxfer_int8))
            else:
                out.append(serve_tfjob_template(
                    name, namespace,
                    scheduler_name=scheduler_name,
                    serve_slots=serve_slots, serve_queue=serve_queue,
                    serve_prefix_blocks=serve_prefix_blocks,
                    serve_batch_sampling=serve_batch_sampling,
                    serve_batch_spec=serve_batch_spec,
                    serve_request_log=serve_request_log,
                    serve_request_log_ring=serve_request_log_ring,
                    serve_spill_mb=serve_spill_mb,
                    kvxfer_dedup=kvxfer_dedup,
                    priority=priority, queue=queue,
                    fleet_scrape_port=fleet_scrape_port,
                    fleet_interval_s=fleet_interval_s,
                    autoscale_min=autoscale_min,
                    autoscale_max=autoscale_max,
                    serve_mesh=serve_mesh,
                    serve_weight=serve_weight))
            if router:
                out.append(router_companion_template(
                    name, namespace, router_port=router_port,
                    policy=router_policy,
                    block_size=router_block_size,
                    affinity_blocks=router_affinity_blocks,
                    retry_budget=router_retry_budget,
                    phase_split_tokens=disagg_phase_tokens
                    if disagg else None))
        return out
    return [
        tfjob_template(f"tfjob-{ts}-{i}", namespace, gpu, tpu, scheduler_name,
                       priority=priority, queue=queue)
        for i in range(n)
    ]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nr-tfjobs", type=int, default=1)
    parser.add_argument("--use-gpu", action="store_true")
    parser.add_argument("--use-tpu", action="store_true")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--scheduler-name", default="default")
    parser.add_argument("--priority", type=int, default=None,
                        help="gang-admission priority (v1alpha2 "
                        "spec.priority; higher wins, may preempt)")
    parser.add_argument("--queue", default=None,
                        help="gang-admission queue label (v1alpha2 "
                        "spec.queue)")
    parser.add_argument("--serve", action="store_true",
                        help="generate resident serving TFJobs "
                        "(k8s_tpu.models.server) instead of training "
                        "jobs, with the engine knobs as env")
    parser.add_argument("--serve-slots", type=int, default=8,
                        help="K8S_TPU_SERVE_SLOTS for --serve jobs")
    parser.add_argument("--serve-queue", type=int, default=64,
                        help="K8S_TPU_SERVE_QUEUE for --serve jobs")
    parser.add_argument("--serve-prefix-blocks", type=int, default=None,
                        help="K8S_TPU_SERVE_PREFIX_BLOCKS for --serve "
                        "jobs (omit = auto-size; 0 disables shared-"
                        "prefix KV reuse)")
    parser.add_argument("--serve-batch-sampling", type=int,
                        choices=(0, 1), default=1,
                        help="K8S_TPU_SERVE_BATCH_SAMPLING for --serve "
                        "jobs (0 = exclusive-lane sampling)")
    parser.add_argument("--serve-batch-spec", type=int,
                        choices=(0, 1), default=1,
                        help="K8S_TPU_SERVE_BATCH_SPEC for --serve jobs "
                        "(0 = exclusive-lane speculative decoding)")
    parser.add_argument("--serve-request-log", type=int,
                        choices=(0, 1), default=1,
                        help="K8S_TPU_REQUEST_LOG for --serve jobs: the "
                        "per-request lifecycle recorder behind "
                        "/debug/requests and /debug/engine (default on; "
                        "0 disables)")
    parser.add_argument("--serve-request-log-ring", type=int,
                        default=None,
                        help="K8S_TPU_REQUEST_LOG_RING for --serve jobs "
                        "(finished-timeline ring bound; omit for the "
                        "512 default)")
    parser.add_argument("--serve-mesh", type=int, default=None,
                        help="multi-host tensor-parallel serving gang "
                        "size: N Worker replicas, replica 0 the HTTP "
                        "chief, the rest plan-replaying workers "
                        "(K8S_TPU_SERVE_MESH; ISSUE 14)")
    parser.add_argument("--serve-weight", type=float, default=None,
                        help="kubeflow.org/fleet-serve-weight annotation: "
                        "relative capacity for the router's weighted "
                        "hash ring (e.g. 4.0 for a 4-chip gang)")
    parser.add_argument("--fleet-scrape-port", type=int,
                        default=SERVE_HTTP_PORT,
                        help="kubeflow.org/fleet-scrape-port annotation + "
                        "K8S_TPU_FLEET_SCRAPE_PORT env on --serve jobs so "
                        "the operator's fleet plane discovers them "
                        "(0 disables; default = the serving container's "
                        "own HTTP port, where /metrics lives — any OTHER "
                        "value must be a sidecar exporter's port, the "
                        "server itself stays on %d)" % SERVE_HTTP_PORT)
    parser.add_argument("--fleet-interval", type=float, default=None,
                        help="surface K8S_TPU_FLEET_INTERVAL_S on --serve "
                        "pods (the operator-side scrape cadence knob)")
    parser.add_argument("--router", action="store_true",
                        help="with --serve: also emit each job's front-"
                        "door companion Pod (python -m k8s_tpu.cmd.router "
                        "--job <ns>/<name>): prefix-affine /v1/generate "
                        "proxy with least-outstanding fallback and clean "
                        "SIGTERM drain (ISSUE 13)")
    parser.add_argument("--router-port", type=int,
                        default=ROUTER_HTTP_PORT,
                        help="the companion router's HTTP port")
    parser.add_argument("--router-policy", default="affine",
                        choices=("affine", "least", "random"),
                        help="placement policy (K8S_TPU_ROUTER_POLICY; "
                        "random is the bench's control arm)")
    parser.add_argument("--router-block-size", type=int, default=None,
                        help="K8S_TPU_ROUTER_BLOCK_SIZE on the companion "
                        "(must match the serving engine's KV block size; "
                        "omit for the default)")
    parser.add_argument("--router-affinity-blocks", type=int,
                        default=None,
                        help="K8S_TPU_ROUTER_AFFINITY_BLOCKS on the "
                        "companion (full prompt blocks fingerprinted; "
                        "omit for the default)")
    parser.add_argument("--router-retry-budget", type=int, default=None,
                        help="K8S_TPU_ROUTER_RETRY_BUDGET on the "
                        "companion (omit for the default)")
    parser.add_argument("--autoscale-min", type=int, default=None,
                        help="spec.autoscale.minReplicas on --serve jobs "
                        "(with --autoscale-max; the operator's autoscaler "
                        "scales the Worker count inside these bounds when "
                        "K8S_TPU_AUTOSCALE is on)")
    parser.add_argument("--autoscale-max", type=int, default=None,
                        help="spec.autoscale.maxReplicas on --serve jobs")
    parser.add_argument("--disagg", action="store_true",
                        help="with --serve: emit the DISAGGREGATED "
                        "two-tier job (Prefill + Decode replica types, "
                        "KV block migration between them; ISSUE 15) "
                        "instead of the single-role Worker job; with "
                        "--router the companion carries the phase-split "
                        "threshold")
    parser.add_argument("--disagg-prefill", type=int, default=1,
                        help="Prefill-tier replica count for --disagg")
    parser.add_argument("--disagg-decode", type=int, default=2,
                        help="Decode-tier replica count for --disagg")
    parser.add_argument("--disagg-phase-tokens", type=int, default=64,
                        help="router phase-split threshold "
                        "(K8S_TPU_ROUTER_PHASE_TOKENS on the companion): "
                        "prompts of at least this many tokens go to the "
                        "Prefill tier")
    parser.add_argument("--kvxfer-port", type=int, default=KVXFER_PORT,
                        help="K8S_TPU_KVXFER_PORT on Decode-tier pods "
                        "(the block-transfer listener)")
    parser.add_argument("--kvxfer-int8", type=int, choices=(0, 1),
                        default=0,
                        help="K8S_TPU_KVXFER_INT8 on Prefill-tier pods: "
                        "quantize fp-pool KV content for transit "
                        "(lossy on fp pools; no-op on int8 pools)")
    parser.add_argument("--serve-spill-mb", type=int, default=None,
                        help="K8S_TPU_SERVE_SPILL_MB: host-RAM KV spill "
                        "tier budget in MB (ISSUE 17; 0 or omitted = "
                        "off).  Counts against the pod memory limit — "
                        "leave that much headroom in resources.limits."
                        "memory")
    parser.add_argument("--kvxfer-dedup", type=int, choices=(0, 1),
                        default=None,
                        help="K8S_TPU_KVXFER_DEDUP: the migration "
                        "block-fingerprint dedup handshake (ISSUE 17). "
                        "Omit for the server default (on); 0 ships "
                        "every block unconditionally")
    parser.add_argument(
        "--dump", action="store_true", help="print manifests instead of creating"
    )
    parser.add_argument("--kube-config-path", default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    jobs = generate(
        args.nr_tfjobs,
        namespace=args.namespace,
        gpu=args.use_gpu,
        tpu=args.use_tpu,
        scheduler_name=args.scheduler_name,
        priority=args.priority,
        queue=args.queue,
        serve=args.serve,
        serve_slots=args.serve_slots,
        serve_queue=args.serve_queue,
        serve_prefix_blocks=args.serve_prefix_blocks,
        serve_batch_sampling=bool(args.serve_batch_sampling),
        serve_batch_spec=bool(args.serve_batch_spec),
        serve_request_log=bool(args.serve_request_log),
        serve_request_log_ring=args.serve_request_log_ring,
        serve_spill_mb=args.serve_spill_mb,
        kvxfer_dedup=(None if args.kvxfer_dedup is None
                      else bool(args.kvxfer_dedup)),
        fleet_scrape_port=args.fleet_scrape_port or None,
        fleet_interval_s=args.fleet_interval,
        router=args.router,
        router_port=args.router_port,
        router_policy=args.router_policy,
        router_block_size=args.router_block_size,
        router_affinity_blocks=args.router_affinity_blocks,
        router_retry_budget=args.router_retry_budget,
        autoscale_min=args.autoscale_min,
        autoscale_max=args.autoscale_max,
        serve_mesh=args.serve_mesh,
        serve_weight=args.serve_weight,
        disagg=args.disagg,
        disagg_prefill=args.disagg_prefill,
        disagg_decode=args.disagg_decode,
        disagg_phase_tokens=args.disagg_phase_tokens,
        kvxfer_port=args.kvxfer_port,
        kvxfer_int8=bool(args.kvxfer_int8),
    )
    if args.dump:
        yaml.safe_dump_all(jobs, sys.stdout)
        return 0

    from k8s_tpu.client.clientset import Clientset
    from k8s_tpu.client.rest import RestClient, kubeconfig_config

    clientset = Clientset(RestClient(kubeconfig_config(args.kube_config_path)))
    for job in jobs:
        if job.get("kind") == "Pod":
            created = clientset.pods(args.namespace).create(job)
            log.info("created router Pod %s", created["metadata"]["name"])
            continue
        created = clientset.tfjobs_unstructured(
            args.namespace, api_version=job["apiVersion"]
        ).create(job)
        log.info("created TFJob %s", created["metadata"]["name"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

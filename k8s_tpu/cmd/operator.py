"""v1 operator binary (reference: cmd/tf-operator/).

Flags mirror cmd/tf-operator/app/options/options.go:39-47.  Run flow
mirrors app.Run (server.go:55-135): cluster config → clients → controller
config → leader election → controller.Run.  Unlike the reference (which
parses chaos-level with the implementation excised), --chaos-level here is
live: while leading, a ChaosMonkey deletes managed pods in the watched
namespace (test clusters only).
"""

from __future__ import annotations

import argparse
import logging
import os
import socket
import sys

import yaml

from k8s_tpu import version
from k8s_tpu.api import v1alpha1
from k8s_tpu.client.clientset import Clientset
from k8s_tpu.util.leader_election import LeaderElectionConfig, LeaderElector
from k8s_tpu.util.signals import merge_stop_events, setup_signal_handler
from k8s_tpu.util.util import get_namespace

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpu-operator")
    p.add_argument("--chaos-level", type=int, default=-1,
                   help="Fault injection: delete up to N managed pods per "
                   "tick (<=0 disables). The reference kept the flag with "
                   "the implementation excised (options.go:40-41); here it "
                   "drives e2e.chaos.ChaosMonkey against the watched "
                   "namespace — test clusters only.")
    p.add_argument("--controller-config-file", default="",
                   help="Path to the accelerator ControllerConfig YAML (server.go:138-156)")
    p.add_argument("--enable-gang-scheduling", action="store_true",
                   help="Create PodDisruptionBudgets for distributed jobs (options.go:46)")
    p.add_argument("--json-log-format", action="store_true")
    p.add_argument("--gc-interval-seconds", type=float, default=600,
                   help="(reserved; resource GC runs via owner references)")
    p.add_argument("--threadiness", type=int, default=1)
    p.add_argument("--namespace", default="",
                   help="Namespace to watch (default: KUBEFLOW_NAMESPACE or all)")
    p.add_argument("--kubeconfig", default=os.environ.get("KUBECONFIG", ""))
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve /metrics + /healthz on this port (0 = off, "
                   "matching the reference, which exposes no endpoint)")
    p.add_argument("--metrics-host", default="0.0.0.0",
                   help="bind address for the metrics endpoint. The "
                   "endpoints are UNAUTHENTICATED: the default binds all "
                   "interfaces because in-pod scrapers must reach them; "
                   "pass 127.0.0.1 to restrict to loopback (the library "
                   "default outside this binary)")
    p.add_argument("--version", action="store_true")
    return p


def read_controller_config(path: str) -> v1alpha1.ControllerConfig:
    """server.go:138-156."""
    if not path:
        return v1alpha1.ControllerConfig()
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    accelerators = {}
    for name, cfg in (raw.get("accelerators") or {}).items():
        accelerators[name] = v1alpha1.AcceleratorConfig(
            volumes=[
                v1alpha1.AcceleratorVolume(
                    name=v.get("name", ""),
                    host_path=v.get("hostPath", ""),
                    mount_path=v.get("mountPath", ""),
                )
                for v in cfg.get("volumes") or []
            ],
            env_vars=[
                v1alpha1.EnvironmentVariableConfig(
                    name=e.get("name", ""), value=e.get("value", "")
                )
                for e in cfg.get("envVars") or []
            ],
        )
    return v1alpha1.ControllerConfig(
        accelerators=accelerators,
        grpc_server_file_path=raw.get("grpcServerFilePath", ""),
    )


def make_backend(kubeconfig: str):
    from k8s_tpu.client.rest import RestClient, get_cluster_config, kubeconfig_config

    if kubeconfig:
        return RestClient(kubeconfig_config(kubeconfig))
    return RestClient(get_cluster_config())


def run(opts, backend=None) -> int:
    if opts.chaos_level > 0 and os.environ.get("K8S_TPU_ALLOW_CHAOS") != "1":
        # The reference shipped this flag inert ("DO NOT USE IN PRODUCTION",
        # options.go:40-41); here it is live, so a second explicit key is
        # required before the leader may delete managed pods.  Fail fast at
        # startup rather than after winning the election.
        raise SystemExit(
            "--chaos-level > 0 deletes managed pods; refusing to start "
            "without K8S_TPU_ALLOW_CHAOS=1 in the environment"
        )
    logging.basicConfig(
        level=logging.INFO,
        format='{"level":"%(levelname)s","msg":"%(message)s","time":"%(asctime)s"}'
        if opts.json_log_format
        else "%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    from k8s_tpu.controller.controller import Controller

    clientset = Clientset(backend if backend is not None else make_backend(opts.kubeconfig))
    config = read_controller_config(opts.controller_config_file)
    controller = Controller(
        clientset,
        config=config,
        enable_gang_scheduling=opts.enable_gang_scheduling,
    )
    stop = setup_signal_handler()

    from k8s_tpu.util.metrics_server import maybe_start

    metrics_server = maybe_start(getattr(opts, "metrics_port", 0),
                                host=getattr(opts, "metrics_host", "0.0.0.0"),
                                health_fn=controller.healthy)

    namespace = opts.namespace or get_namespace()
    elector = LeaderElector(
        clientset,
        LeaderElectionConfig(
            namespace=namespace,
            name="tf-operator",
            identity=f"{socket.gethostname()}-{os.getpid()}",
        ),
    )

    def on_started_leading(stop_work):
        # chaos only while LEADING: a standby replica injecting faults
        # would double the configured rate and outlive its lease
        monkey = None
        if opts.chaos_level > 0:
            from k8s_tpu.e2e.chaos import ChaosMonkey

            monkey = ChaosMonkey(
                clientset, namespace, level=opts.chaos_level
            ).start()
            log.warning(
                "chaos level %d: injecting managed-pod faults in %s",
                opts.chaos_level, namespace)
        try:
            controller.run(
                opts.threadiness, stop_event=merge_stop_events(stop, stop_work)
            )
        finally:
            if monkey is not None:
                monkey.stop()

    def on_stopped_leading():
        log.error("leader election lost")
        os._exit(1)

    try:
        elector.run_or_die(on_started_leading, on_stopped_leading)
    finally:
        if metrics_server is not None:
            metrics_server.stop()
    return 0


def main() -> int:
    opts = build_parser().parse_args()
    if opts.version:
        version.print_version("tpu-operator")
        return 0
    return run(opts)


if __name__ == "__main__":
    sys.exit(main())

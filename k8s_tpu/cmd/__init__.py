"""Operator process entrypoints (reference: cmd/)."""

"""Front-door router binary with informer-cache discovery (ISSUE 13).

    python -m k8s_tpu.cmd.router --job default/serve-lm --port 8080

Builds its OWN pod informer (the operator's zero-apiserver-call
discovery substrate — one LIST + a watch, then pure cache reads) and
wires ``fleet.targets_from_pods`` over the fleet-scrape index as the
router's ``targets_fn``: pods join the ring as they go Running and
leave as they terminate, with no per-request apiserver traffic.  The
stdlib-only core lives in :mod:`k8s_tpu.router`; this wrapper carries
the client-layer imports that package may not (the same split as
``cmd/operator_v2`` over ``controller_v2``).

SIGTERM drains: new requests 503 with Retry-After while in-flight ones
complete, then the process exits 0.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading

from k8s_tpu import fleet as fleet_mod
from k8s_tpu import router as router_mod
from k8s_tpu.util.signals import setup_signal_handler

log = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpu-serve-router")
    p.add_argument("--master", default="", help="apiserver URL override")
    p.add_argument("--kubeconfig", default=os.environ.get("KUBECONFIG", ""))
    p.add_argument("--job", required=True,
                   help="serving TFJob key (namespace/name) to front")
    p.add_argument("--host", default="0.0.0.0",
                   help="bind address (the front door is meant to be "
                   "reachable; pass 127.0.0.1 to restrict)")
    p.add_argument("--port", type=int,
                   default=router_mod._int_from_env(router_mod.ENV_PORT,
                                                    8080))
    p.add_argument("--policy", choices=router_mod.VALID_POLICIES,
                   default=router_mod.policy_from_env())
    p.add_argument("--block-size", type=int,
                   default=router_mod.block_size_from_env())
    p.add_argument("--affinity-blocks", type=int,
                   default=router_mod.affinity_blocks_from_env())
    p.add_argument("--retry-budget", type=int,
                   default=router_mod.retry_budget_from_env())
    p.add_argument("--phase-split-tokens", type=int,
                   default=router_mod.phase_tokens_from_env() or 0,
                   help="route prompts of at least this many tokens to "
                   "the prefill tier (disaggregated phase split, "
                   "K8S_TPU_ROUTER_PHASE_TOKENS; 0 = off)")
    p.add_argument("--hedge-s", type=float,
                   default=router_mod.hedge_s_from_env(),
                   help="hedge a stuck idempotent request against the "
                   "next ring candidate after this many seconds "
                   "(K8S_TPU_ROUTER_HEDGE_S; 0 = off)")
    p.add_argument("--drain-timeout", type=float, default=30.0)
    return p


def run(opts, backend=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    from k8s_tpu.client.gvr import PODS
    from k8s_tpu.client.informer import (
        FLEET_SCRAPE_INDEX,
        FLEET_SCRAPE_KEY,
        SharedInformerFactory,
        index_fleet_scrape_pods,
    )
    from k8s_tpu.cmd.operator_v2 import make_backend

    if "/" not in opts.job:
        # targets_from_pods keys jobs as "namespace/name"; a bare name
        # would silently match zero targets forever
        opts.job = f"default/{opts.job}"
    backend = backend if backend is not None else make_backend(opts)
    factory = SharedInformerFactory(backend)
    pod_informer = factory.informer_for(PODS)
    pod_informer.store.add_index(FLEET_SCRAPE_INDEX,
                                 index_fleet_scrape_pods)
    factory.start()
    if not factory.wait_for_cache_sync(30):
        raise RuntimeError("failed to wait for pod cache to sync")

    job = opts.job

    def targets_fn():
        return [t for t in fleet_mod.targets_from_pods(
            pod_informer.store.by_index(FLEET_SCRAPE_INDEX,
                                        FLEET_SCRAPE_KEY))
                if t.job == job]

    router = router_mod.Router(
        targets_fn, job=job, policy=opts.policy,
        block_size=opts.block_size,
        affinity_blocks=opts.affinity_blocks,
        retry_budget=opts.retry_budget,
        phase_split_tokens=opts.phase_split_tokens or None,
        hedge_s=opts.hedge_s)
    server = router_mod.RouterServer(router, host=opts.host,
                                     port=opts.port)
    router_mod.set_active(router)
    server.start()
    print(f"READY http://{opts.host}:{server.port}", flush=True)
    stop = setup_signal_handler()
    drained = threading.Event()

    def _drain():
        stop.wait()
        log.info("router: signal — draining (budget %.1fs)",
                 opts.drain_timeout)
        server.drain_and_stop(opts.drain_timeout)
        drained.set()

    threading.Thread(target=_drain, daemon=True,
                     name="router-drain").start()
    drained.wait()
    router_mod.set_active(None)
    factory.stop()
    return 0


def main() -> int:
    return run(build_parser().parse_args())


if __name__ == "__main__":
    sys.exit(main())

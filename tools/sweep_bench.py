#!/usr/bin/env python3
"""Perf sweep driver: runs bench.py across a variant matrix, one subprocess
per variant (XLA flags and env knobs need fresh processes), and prints a
ranked table.  Used to chase the round-3 headline targets:

- ResNet: conv vs s2d stem (BENCH_RESNET_STEM);
- transformer: flash tile sizes (BENCH_FLASH_BLOCK_Q/K).

Each variant runs BENCH_ONLY-scoped with reduced repeats so one sweep fits
in a relay-friendly window; the winner is then re-run at full repeats by
the operator before committing numbers to BENCH_BASELINE/BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RESNET_VARIANTS = [
    {"name": "conv-stem", "env": {"BENCH_RESNET_STEM": "conv"}},
    {"name": "s2d-stem", "env": {"BENCH_RESNET_STEM": "s2d"}},
]

TRANSFORMER_VARIANTS = [
    {"name": "flash-512x1024", "env": {}},  # kernel defaults
    {"name": "flash-256x512",
     "env": {"BENCH_FLASH_BLOCK_Q": "256", "BENCH_FLASH_BLOCK_K": "512"}},
    {"name": "flash-512x512",
     "env": {"BENCH_FLASH_BLOCK_Q": "512", "BENCH_FLASH_BLOCK_K": "512"}},
    {"name": "flash-1024x1024",
     "env": {"BENCH_FLASH_BLOCK_Q": "1024", "BENCH_FLASH_BLOCK_K": "1024"}},
    {"name": "flash-256x1024",
     "env": {"BENCH_FLASH_BLOCK_Q": "256", "BENCH_FLASH_BLOCK_K": "1024"}},
    # sliding-window A/B at seq 2048 (vs the full-causal seq-2048 control):
    # measures the bounded-grid O(L*window) claim on hardware.  Separate
    # GROUP: these are an A/B pair, not tile candidates — mixing them into
    # the tile ranking would let a window (cheaper per token by design)
    # "win" the tile sweep.  Longer per-variant budget: ~2x tokens and up
    # to 4x attention work per step plus a fresh seq-2048 compile.
    {"name": "swa-2048-w512", "group": "swa", "timeout": 1300,
     "env": {"BENCH_SEQ": "2048", "BENCH_WINDOW": "512"}},
    {"name": "causal-2048-control", "group": "swa", "timeout": 1300,
     "env": {"BENCH_SEQ": "2048"}},
]


def run_variant(which: str, variant: dict, repeats: int, timeout: float):
    timeout = variant.get("timeout", timeout)
    env = dict(os.environ)
    env.update(variant["env"])
    env.update({
        "BENCH_ONLY": which,
        "BENCH_REPEATS": str(repeats),
        "BENCH_NO_CONTROL": "1",
        # variants explore non-default configs; keep them out of the
        # last-good-on-hardware record (the sweep table is their artifact)
        "BENCH_NO_PERSIST": "1",
        # the caller owns retries — a mid-sweep relay death must fail each
        # remaining variant in ~1min, not burn the default 600s preflight
        # window per variant
        "BENCH_PREFLIGHT_WINDOW": "60",
        # floor: a small --timeout must not arm bench.py's watchdog with a
        # zero/negative budget (it would os._exit immediately)
        "BENCH_TOTAL_TIMEOUT": str(max(60.0, timeout - 30)),
    })
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"name": variant["name"], "error": "timeout"}
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return {"name": variant["name"],
                "error": tail[-1][:160] if tail else f"rc={r.returncode}"}
    try:
        out = json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"name": variant["name"], "error": "bad output"}
    key = ("value" if which == "resnet"
           else "transformer_tokens_per_sec_per_chip")
    std_key = "resnet50_std" if which == "resnet" else "transformer_std"
    return {"name": variant["name"], "value": out.get(key),
            "std": out.get(std_key), "raw": out}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("which", choices=["resnet", "transformer"])
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--timeout", type=float, default=900.0,
                   help="per-variant wall clock (compile + repeats)")
    args = p.parse_args(argv)

    variants = RESNET_VARIANTS if args.which == "resnet" \
        else TRANSFORMER_VARIANTS
    results = []
    for v in variants:
        print(f"sweep: running {v['name']} ...", file=sys.stderr, flush=True)
        res = run_variant(args.which, v, args.repeats, args.timeout)
        results.append(res)
        print(f"sweep: {v['name']} -> "
              f"{res.get('value', res.get('error'))}",
              file=sys.stderr, flush=True)

    by_name = {v["name"]: v for v in variants}
    ok = [r for r in results if "value" in r and r["value"]]
    # rank/report per GROUP: the default group competes for the config
    # crown; A/B groups (e.g. "swa") are comparisons, never winners
    main_ok = [r for r in ok if not by_name[r["name"]].get("group")]
    main_ok.sort(key=lambda r: -r["value"])
    for r in sorted(ok, key=lambda r: -r["value"]):
        group = by_name[r["name"]].get("group")
        tag = f" [{group}]" if group else ""
        print(f"{r['name']:>22}: {r['value']:>10.1f} "
              f"± {r.get('std') or 0:.1f}{tag}")
    for r in results:
        if "error" in r:
            print(f"{r['name']:>22}: ERROR {r['error']}")
    if ok:
        # emit whenever ANYTHING succeeded: if the relay ate every tile
        # variant but the A/B groups landed, their hardware evidence must
        # still reach the machine-readable line ("winner" becomes optional)
        out = {"variants_ok": len(ok), "variants_total": len(variants)}
        if main_ok:
            out["winner"] = main_ok[0]["name"]
            out["value"] = main_ok[0]["value"]
        groups = sorted({by_name[r["name"]].get("group")
                         for r in ok if by_name[r["name"]].get("group")})
        for g in groups:
            out[f"{g}_ab"] = {r["name"]: r["value"] for r in ok
                              if by_name[r["name"]].get("group") == g}
        print(json.dumps(out))
    # Partial success exits nonzero: a caller that marks a sweep "done" on
    # rc=0 must not lose the variants the relay ate — a winner picked from
    # a one-variant table is not an A/B.
    if len(ok) == len(variants):
        return 0
    return 1 if not ok else 3


if __name__ == "__main__":
    sys.exit(main())

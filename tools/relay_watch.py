#!/usr/bin/env python3
"""TPU relay watcher: probe until the relay answers, then IMMEDIATELY run
the round's measurement battery, persisting each result the moment it lands.

Round 3 lost its entire hardware window because the relay served for ~17
minutes and the measurements weren't queued behind a watcher.  This driver
fixes that operationally:

- probes the relay with a cheap subprocess matmul every --interval seconds
  (a hung probe is killed; it never poisons this process);
- the moment a probe succeeds, runs the measurement plan in priority order
  (cheapest/highest-value first), so even a short relay window yields the
  headline A/Bs;
- every item's JSON line + stderr tail is appended to the sweep dir
  (sweeps_r05/ by default; $RELAY_SWEEP_DIR overrides) as it completes, and
  bench.py itself persists BENCH_LASTGOOD.json incrementally, so a
  mid-battery relay death keeps everything measured so far;
- items that fail (relay died) stay pending: the watcher goes back to
  probing and resumes the remaining plan on the next window.

Run it in the background:  python tools/relay_watch.py >> relay_watch.log 2>&1
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUTDIR = os.path.join(REPO, os.environ.get("RELAY_SWEEP_DIR", "sweeps_r05"))
STATE = os.path.join(OUTDIR, "state.json")
PY = sys.executable

sys.path.insert(0, REPO)
import bench  # noqa: E402  (probe protocol's single source of truth)
import sweep_bench  # noqa: E402  (variant matrix's single source of truth)


def now() -> str:
    return bench._utcnow()


def log(msg: str) -> None:
    print(f"[{now()}] relay_watch: {msg}", flush=True)


# Priority order (VERDICT r4 "next round" #1): spend relay windows
# COST-AWARE — round 4's only green window (8 min) died inside a fresh
# 23-minute resnet_s2d compile and landed nothing.  Round-5 order:
#   (a) default-config persist items first (fused_ce_off = the headline
#       transformer at repeats>=3, resnet_conv = the ~2-min provenance
#       refresh) — these fix the repeats=1/std=0.0 and stale-provenance
#       weaknesses with the smallest possible compile bill;
#   (b) the fused-CE A/B partner (shares most of the transformer program);
#   (c) decode + vit: the unmeasured inference/ViT perf identities
#       (VERDICT r4 weak #5), moderate compiles;
#   (d) flash-tile candidates (same shapes, different kernel tiles);
#   (e) fresh-compile gambles LAST: resnet_s2d (the known 23-min compile)
#       and the seq-2048 SWA pair;
#   (f) full_bench to refresh everything at full repeats.
#
# Items are WINDOW-SIZED: one variant per item, 3 repeats (statistical
# hygiene: bench.py now refuses to stamp last-good at repeats=1).  A/B
# pairs are adjacent so a single healthy window measures both sides.
# A persistent XLA compilation cache (shared dir below) lets a re-attempt
# after a mid-compile relay death skip straight to measurement when the
# backend supports executable serialization.
CACHE_ENV = {
    "JAX_COMPILATION_CACHE_DIR": os.path.join(REPO, ".jax_cache"),
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1",
}


def _variant_env(variants: list[dict], name: str) -> dict:
    for v in variants:
        if v["name"] == name:
            return dict(v["env"])
    raise KeyError(f"sweep_bench variant {name!r} not found")


def build_plan() -> list[dict]:
    bench_py = os.path.join(REPO, "bench.py")
    base = {
        "BENCH_REPEATS": "3",
        "BENCH_NO_CONTROL": "1",
        "BENCH_PREFLIGHT_WINDOW": "60",
        # a hung phase (relay death) fails the item in ~10min instead of
        # burning the whole 23min watchdog budget — more attempts per
        # relay window (bench.py with_retries BENCH_PHASE_TIMEOUT)
        "BENCH_PHASE_TIMEOUT": "600",
        **CACHE_ENV,
    }

    def item(label, extra_env, timeout=1500, only=None, persist=False,
             phase_timeout=None):
        env = dict(base)
        env.update(extra_env)
        if only:
            env["BENCH_ONLY"] = only
        if phase_timeout is not None:
            env["BENCH_PHASE_TIMEOUT"] = str(phase_timeout)
        if not persist:
            # non-default configs stay out of the last-good-on-hardware
            # record; the battery log (OUTDIR) is their artifact
            env["BENCH_NO_PERSIST"] = "1"
        # bench's watchdog must fire before the subprocess kill so it can
        # emit its diagnostic + partial evidence before rc=124 erases it
        env["BENCH_TOTAL_TIMEOUT"] = str(timeout - 120)
        return {"label": label, "argv": [PY, bench_py], "env": env,
                "timeout": timeout}

    rn = sweep_bench.RESNET_VARIANTS
    tf = sweep_bench.TRANSFORMER_VARIANTS
    # flash tile candidates: the sweep matrix's non-group entries with a
    # non-default env (the default tile is measured by the fused_ce_off row)
    tiles = [v for v in tf if v["env"] and not v.get("group")]
    # the SWA pair measures the O(L*window) claim at seq 2048: ~2x tokens
    # and up to 4x attention work per step, plus a fresh seq-2048 compile
    swa = [v for v in tf if v.get("group") == "swa"]
    return [
        # (a) default configs, persisted: headline transformer at 3 repeats
        # (kills the std=0.0 weakness) and the ~2-min conv ResNet
        # provenance refresh
        item("fused_ce_off", {}, only="transformer", persist=True),
        item("resnet_conv", _variant_env(rn, "conv-stem"), only="resnet",
             persist=True),
        # (b) the fused-CE A/B partner — mostly-shared transformer program
        item("fused_ce_on", {"BENCH_FUSED_CE": "1"}, only="transformer"),
        # (c) unmeasured perf identities: decode tokens/s + ViT images/s,
        # then the serving-depth A/Bs (prefill one-shot vs chunked, beam-4
        # overhead, batch sweep point — 4 fresh compiles, so after the
        # cheap identities)
        item("decode", {}, only="decode", persist=True),
        item("vit", {}, only="vit", persist=True),
        # int8 KV cache A/B vs the bf16-cache decode above (same shapes,
        # one new compile; non-default config so it never persists)
        item("decode_kv_int8", {"BENCH_KV_CACHE": "int8"}, only="decode"),
        item("decode_depth", {}, only="decode_depth", persist=True,
             timeout=2100, phase_timeout=900),
        # (d) flash-tile candidates (same model shapes, new kernel tiles)
        *[item("flash_" + v["name"].removeprefix("flash-"), dict(v["env"]),
               only="transformer") for v in tiles],
        # (e) fresh-compile gambles LAST: s2d stem (died at 1382s compile in
        # r4 — give it room) and the seq-2048 SWA pair
        item("resnet_s2d", _variant_env(rn, "s2d-stem"), only="resnet",
             timeout=2400, phase_timeout=2000),
        *[item(v["name"].replace("-", "_"), dict(v["env"]),
               only="transformer", timeout=1800, phase_timeout=900)
          for v in swa],
        # (f) the full default bench at full repeats
        {"label": "full_bench",
         "argv": [PY, bench_py],
         "env": {"BENCH_PREFLIGHT_WINDOW": "120",
                 "BENCH_TOTAL_TIMEOUT": "2550",
                 "BENCH_PHASE_TIMEOUT": "900",
                 **CACHE_ENV},
         "timeout": 2700},
    ]


def probe(timeout: float) -> str:
    status, detail = bench._probe_subprocess(timeout)
    if status not in ("ok", "hang"):
        log(f"probe: {status}: {detail}")
    return status


def load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"done": [], "results": {}}


def save_state(state: dict) -> None:
    os.makedirs(OUTDIR, exist_ok=True)
    bench._atomic_write_json(STATE, state)


def run_item(item: dict) -> dict:
    env = dict(os.environ)
    env.update(item["env"])
    t0 = time.time()
    try:
        r = subprocess.run(item["argv"], env=env, capture_output=True,
                           text=True, timeout=item["timeout"], cwd=REPO)
        rc = r.returncode
        stdout, stderr = r.stdout, r.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        stdout = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        stderr = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
    parsed = None
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
            break
        except ValueError:
            continue
    res = {"label": item["label"], "rc": rc, "seconds": round(time.time() - t0, 1),
           "finished_at": now(), "parsed": parsed,
           "stderr_tail": (stderr or "").strip().splitlines()[-8:]}
    if (parsed or {}).get("results_from_last_good") or \
            (parsed or {}).get("partial"):
        # bench fell back to stale/partial evidence mid-item — the relay
        # died; classify the ATTEMPT as failed before the artifact is
        # written so the battery log never records it as a measurement
        res["rc"] = rc or 75
        res["stale_fallback"] = True
    os.makedirs(OUTDIR, exist_ok=True)
    with open(os.path.join(OUTDIR, f"{item['label']}.json"), "w") as f:
        json.dump(res, f, indent=1)
        f.write("\n")
    with open(os.path.join(OUTDIR, "battery.jsonl"), "a") as f:
        f.write(json.dumps(res) + "\n")
    return res


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--interval", type=float, default=600.0,
                   help="seconds between relay probes while down")
    p.add_argument("--probe-timeout", type=float, default=120.0)
    p.add_argument("--max-hours", type=float, default=11.0,
                   help="give up after this much wall clock")
    args = p.parse_args(argv)

    deadline = time.monotonic() + args.max_hours * 3600
    state = load_state()
    # failure counts never persist across watcher restarts: a crashed or
    # re-launched watcher must not pre-load an item toward permanent-skip
    state["failed"] = {}
    plan = [i for i in build_plan() if i["label"] not in state["done"]]
    log(f"plan: {[i['label'] for i in plan]}")
    MAX_ITEM_FAILURES = 3
    # A deterministic bug fails fast (bad env → fatal preflight, argparse
    # error, crash on import).  A relay death mid-item burns most of the
    # item's budget before failing — and the relay may well be back up by
    # re-probe time (windows can be shorter than an item), so "probe ok
    # after failure" alone must NOT classify the failure as deterministic.
    FAST_FAILURE_S = 300
    while plan and time.monotonic() < deadline:
        status = probe(args.probe_timeout)
        if status == "fatal":
            # deterministic code/setup failure — re-probing for 11 hours
            # cannot fix it and would burn the whole hardware window
            log("probe failure is not relay-shaped; aborting watcher")
            return 2
        if status != "ok":
            log(f"relay down ({status}); next probe in {args.interval:.0f}s")
            time.sleep(args.interval)
            continue
        log("relay UP — running battery")
        for item in plan:
            log(f"running {item['label']} ...")
            res = run_item(item)
            if res["rc"] == 0 and res["parsed"] is not None:
                log(f"{item['label']} OK in {res['seconds']}s: "
                    f"{json.dumps(res['parsed'])[:300]}")
                state["done"].append(item["label"])
                state["results"][item["label"]] = res["parsed"]
                save_state(state)
                continue
            log(f"{item['label']} FAILED rc={res['rc']} in {res['seconds']}s "
                f"({(res['stderr_tail'] or ['?'])[-1][:160]})")
            # Slow failure ⇒ relay-shaped (died mid-item) even if a re-probe
            # succeeds — relay windows can be shorter than an item, so
            # "relay up now" says nothing about why a 40-minute run died.
            # Leave the item pending and go back to probing.  Only FAST
            # failures with the relay still up count as deterministic
            # attempts; after MAX_ITEM_FAILURES of those, skip the item so
            # it can't starve the rest of the plan.
            if res["seconds"] >= FAST_FAILURE_S:
                break
            if probe(args.probe_timeout) != "ok":
                break
            fails = state["failed"].get(item["label"], 0) + 1
            state["failed"][item["label"]] = fails
            if fails >= MAX_ITEM_FAILURES:
                log(f"{item['label']} failed fast {fails}x with relay up — "
                    "marking permanently failed")
                state["done"].append(item["label"])
                state["results"][item["label"]] = {"error": "permanent",
                                                   "rc": res["rc"]}
            save_state(state)
        plan = [i for i in build_plan()
                if i["label"] not in state["done"]]
        if plan:
            time.sleep(args.interval / 2)
    if plan:
        log(f"giving up with pending items: {[i['label'] for i in plan]}")
        return 1
    permanent = [k for k, v in state["results"].items()
                 if isinstance(v, dict) and v.get("error")]
    if permanent:
        log(f"battery complete with permanent failures: {permanent}")
        return 1
    log("battery complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
